"""End-to-end: training converges, cached decode ≡ reference-shaped decode."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from csat_tpu.data.dataset import ASTDataset, iterate_batches
from csat_tpu.data.vocab import load_vocab
from csat_tpu.train import Trainer, greedy_decode, greedy_decode_nocache, run_test
from csat_tpu.train.state import make_model


def test_train_smoke(synthetic_corpus, tiny_config):
    """Fast-tier end-to-end slice: a 2-epoch full-attention fit plus one
    greedy decode finishes and produces finite numbers."""
    cfg = tiny_config.replace(
        data_dir=synthetic_corpus, full_att=True, num_epochs=2,
        val_interval=2, dropout=0.0, attention_dropout=0.0,
    )
    trainer = Trainer(cfg, log=lambda s: None)
    train_ds = ASTDataset(cfg, "train", trainer.src_vocab, trainer.tgt_vocab)
    state, history = trainer.fit(train_ds, None)
    assert np.isfinite(history["loss"][-1])
    # cold-start contract (ROADMAP item a): fit compiles the train step
    # exactly ONCE — the initial state is mesh-committed before step 1, so
    # the step-1 output's committed sharding cannot force a second compile
    # of the same program (~12s each on the CPU box before the fix)
    assert trainer.train_step.cache_size() == 1, (
        f"fit built {trainer.train_step.cache_size()} train-step programs; "
        "the initial state must be mesh-committed so it compiles once")
    batch = next(iterate_batches(train_ds, 8, shuffle=False))
    out = np.asarray(
        greedy_decode(trainer.model, {"params": state.params}, batch, jax.random.key(0))
    )
    assert out.shape == (8, cfg.max_tgt_len - 1)


def test_initial_params_injection(synthetic_corpus, tiny_config):
    """``Trainer.initial_params`` (the init-parity lever,
    ``tools/torch_init.py``) replaces the flax init verbatim while keeping
    zero optimizer moments; a wrong-shaped tree is rejected up front."""
    cfg = tiny_config.replace(
        data_dir=synthetic_corpus, full_att=True, num_epochs=1,
        dropout=0.0, attention_dropout=0.0,
    )
    trainer = Trainer(cfg, log=lambda s: None)
    train_ds = ASTDataset(cfg, "train", trainer.src_vocab, trainer.tgt_vocab)
    example = next(iterate_batches(train_ds, cfg.batch_size, shuffle=False))
    base = trainer.init_state(example)
    marked = jax.tree.map(lambda p: np.full_like(np.asarray(p), 0.125), base.params)
    trainer.initial_params = marked
    state = trainer.init_state(example)
    assert float(np.asarray(jax.tree.leaves(state.params)[0]).ravel()[0]) == 0.125
    # wrong shapes must fail loudly, not train silently mis-assembled
    trainer.initial_params = jax.tree.map(
        lambda p: np.zeros(np.asarray(p).shape + (1,), np.float32), base.params)
    with pytest.raises(AssertionError):
        trainer.init_state(example)


@pytest.fixture(scope="module")
def trained(synthetic_corpus, tiny_config):
    """Train the CPU-smoke config (full attention, ref python_full_att) to
    overfit the small synthetic corpus."""
    cfg = tiny_config.replace(
        data_dir=synthetic_corpus,
        full_att=True,
        num_epochs=40,
        val_interval=20,
        learning_rate=3e-4,
        dropout=0.0,
        attention_dropout=0.0,
    )
    trainer = Trainer(cfg, log=lambda s: None)
    train_ds = ASTDataset(cfg, "train", trainer.src_vocab, trainer.tgt_vocab)
    val_ds = ASTDataset(cfg, "dev", trainer.src_vocab, trainer.tgt_vocab)
    state, history = trainer.fit(train_ds, val_ds)
    return cfg, trainer, state, history, train_ds, val_ds


@pytest.mark.slow
def test_loss_decreases(trained):
    _, _, _, history, _, _ = trained
    losses = history["loss"]
    assert losses[-1] < losses[0] * 0.3, losses


@pytest.mark.slow
def test_val_bleu_learns(trained):
    _, _, _, history, _, _ = trained
    assert history["best_bleu"] > 0.35, history["val_bleu"]


@pytest.mark.slow
def test_full_test_metrics(trained, synthetic_corpus):
    cfg, trainer, state, history, _, _ = trained
    test_ds = ASTDataset(cfg, "test", trainer.src_vocab, trainer.tgt_vocab)
    scores = run_test(
        trainer.model, history["best_params"], test_ds, cfg, trainer.tgt_vocab,
        jax.random.key(0),
    )
    assert set(scores) == {"bleu", "rouge_l", "meteor"}
    assert scores["bleu"] > 25.0  # x100 scale
    assert scores["rouge_l"] > 25.0
    assert scores["meteor"] > 10.0


@pytest.mark.slow
def test_cached_decode_matches_nocache(trained):
    """KV-cache scan decode must emit exactly the tokens the reference-shaped
    full-prefix re-run emits."""
    cfg, trainer, state, history, _, val_ds = trained
    batch = next(iterate_batches(val_ds, 8, shuffle=False))
    variables = {"params": history["best_params"]}
    key = jax.random.key(42)
    fast = np.asarray(greedy_decode(trainer.model, variables, batch, key))
    slow = np.asarray(greedy_decode_nocache(trainer.model, variables, batch, key))
    np.testing.assert_array_equal(fast, slow)


@pytest.mark.slow
def test_sbm_training_step_runs(synthetic_corpus, tiny_config):
    """One SBM (sparse-attention) train step: finite loss, sparsity in (0,1),
    grads flow to cluster embeddings through the STE."""
    from csat_tpu.train import make_train_step, default_optimizer
    from csat_tpu.train.state import create_train_state

    cfg = tiny_config.replace(data_dir=synthetic_corpus, full_att=False)
    sv, tv = load_vocab(synthetic_corpus)
    ds = ASTDataset(cfg, "train", sv, tv)
    batch = next(iterate_batches(ds, cfg.batch_size, shuffle=False))
    model = make_model(cfg, sv.size(), tv.size())
    tx = default_optimizer(cfg)
    state = create_train_state(model, tx, batch, seed=0)
    step = make_train_step(model, tx, cfg)
    before = state.params["encoder"]["transformer_0"]["SBMAttention_0"]["clusters"]
    before = np.array(before)
    state, metrics = step(state, batch)
    assert np.isfinite(float(metrics["loss"]))
    assert 0.0 < float(metrics["sparsity"]) < 1.0
    after = np.asarray(state.params["encoder"]["transformer_0"]["SBMAttention_0"]["clusters"])
    assert not np.array_equal(before, after), "cluster embeddings did not update"


def test_bfloat16_train_step_and_decode(synthetic_corpus, tiny_config):
    """compute_dtype='bfloat16' (the MXU production dtype and the bench's
    headline variants): finite loss, params stay fp32 (master weights),
    decode produces valid token ids. Previously only bench.py exercised
    bf16 — a dtype regression would first appear as a failed measurement."""
    from csat_tpu.train import make_train_step, default_optimizer
    from csat_tpu.train.state import create_train_state

    cfg = tiny_config.replace(
        data_dir=synthetic_corpus, compute_dtype="bfloat16")
    sv, tv = load_vocab(synthetic_corpus)
    ds = ASTDataset(cfg, "train", sv, tv)
    batch = next(iterate_batches(ds, cfg.batch_size, shuffle=False))
    model = make_model(cfg, sv.size(), tv.size())
    tx = default_optimizer(cfg)
    state = create_train_state(model, tx, batch, seed=0)
    leaves = jax.tree.leaves(state.params)
    assert all(x.dtype == jnp.float32 for x in leaves), "master weights must stay fp32"
    step = make_train_step(model, tx, cfg)
    state, metrics = step(state, batch)
    assert np.isfinite(float(metrics["loss"]))
    state, metrics = step(state, batch)
    assert np.isfinite(float(metrics["loss"]))

    y = greedy_decode(model, {"params": state.params}, batch, jax.random.key(0))
    y = np.asarray(y)
    assert y.shape[0] == cfg.batch_size
    assert ((y >= 0) & (y < tv.size())).all()


@pytest.mark.slow
def test_prefetch_matches_synchronous(synthetic_corpus, tiny_config):
    """Input double-buffering is a pipeline change, not a semantics change:
    identical batch order, identical loss history."""
    from csat_tpu.train import Trainer

    def run(depth):
        cfg = tiny_config.replace(
            data_dir=synthetic_corpus, num_epochs=2, prefetch=depth)
        trainer = Trainer(cfg, log=lambda *_: None)
        sv, tv = load_vocab(synthetic_corpus)
        ds = ASTDataset(cfg, "train", sv, tv)
        _, history = trainer.fit(ds, None)
        return history["loss"]

    np.testing.assert_allclose(run(2), run(0), rtol=0, atol=0)


@pytest.mark.slow
def test_observability_trace_and_scalars(synthetic_corpus, tiny_config, tmp_path):
    """cfg.profile emits a jax.profiler trace for the first epoch and
    cfg.scalar_log streams epoch records to scalars.jsonl (the reference's
    TensorBoard + ProgressBar surface, script/train.py:210-233)."""
    cfg = tiny_config.replace(
        data_dir=synthetic_corpus, num_epochs=1, profile=True,
        scalar_log=True, output_dir=str(tmp_path),
    )
    trainer = Trainer(cfg, log=lambda *_: None)
    ds = ASTDataset(cfg, "train", trainer.src_vocab, trainer.tgt_vocab)
    trainer.fit(ds, None)

    trace_dir = os.path.join(trainer.output_dir, "trace")
    assert os.path.isdir(trace_dir) and os.listdir(trace_dir), "no trace emitted"
    import json

    scalars = os.path.join(trainer.output_dir, "scalars.jsonl")
    with open(scalars) as f:
        recs = [json.loads(line) for line in f]
    assert any("loss" in r and r.get("epoch") == 1 for r in recs)

    # ISSUE 7: the profiled epoch also exports the host-span timeline as
    # valid Chrome trace-event JSON next to the device trace
    from csat_tpu.obs import load_chrome_trace, validate_chrome_trace

    host = os.path.join(trainer.output_dir, "host_trace.json")
    assert os.path.exists(host), "no host trace exported"
    obj = load_chrome_trace(host)
    assert validate_chrome_trace(obj) == []
    names = {e["name"] for e in obj["traceEvents"]}
    assert {"train.data", "train.step"} <= names

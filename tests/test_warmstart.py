"""Warm-start executable store (ISSUE 13 tentpole, serve/warmstart.py).

Pins the store's contracts at two levels:

* **store unit** — root resolution precedence (``CSAT_TPU_NO_CACHE`` >
  explicit dir > cache-root nesting), key sensitivity to every field,
  the full structured miss-reason vocabulary (``disabled | absent |
  corrupt_header | digest_mismatch | jaxlib_mismatch``), atomic save /
  verified load round-trip, the ``corrupt_entries`` chaos hook, and an
  unwritable root degrading to a disabled store — never an exception;
* **engine integration** — a cold engine on an empty store records only
  structured misses and seeds the store; a second engine warm-starts
  every program (hits, zero misses) with BIT-IDENTICAL generation; a
  store-off engine matches too (the cold path compiles the same
  exported StableHLO); corrupting every entry yields structured
  ``digest_mismatch`` fallbacks and a successful compile-path bring-up,
  which re-seeds the store.
"""

import json
import types

import numpy as np
import pytest

from csat_tpu.data.toy import random_request_sample
from csat_tpu.serve import ServeEngine, collate_requests
from csat_tpu.serve.warmstart import WarmStartStore, store_root

SRC_V, TGT_V, TRIP_V = 200, 300, 50


# ---------------------------------------------------------------------------
# store unit: keying, roundtrip, miss reasons, degradation
# ---------------------------------------------------------------------------


def test_store_root_precedence(monkeypatch, tmp_path):
    monkeypatch.setenv("CSAT_TPU_NO_CACHE", "1")
    assert store_root(None) is None  # kill switch wins over everything
    assert store_root(types.SimpleNamespace(serve_warmstart_dir="/x")) is None
    monkeypatch.setenv("CSAT_TPU_NO_CACHE", "0")
    cfg = types.SimpleNamespace(serve_warmstart_dir=str(tmp_path / "explicit"))
    assert store_root(cfg) == str(tmp_path / "explicit")  # verbatim
    monkeypatch.setenv("CSAT_TPU_CACHE_DIR", str(tmp_path / "cache"))
    root = store_root(types.SimpleNamespace(serve_warmstart_dir=""))
    assert root == str(tmp_path / "cache" / "warmstart")  # nests under cache


def test_key_is_sensitive_to_every_field():
    fields = {"mesh": "1xcpu", "git": "abc", "params": "d0", "slots": 2}
    k0 = WarmStartStore.key("decode", fields)
    assert k0 == WarmStartStore.key("decode", dict(fields))  # stable
    assert k0 != WarmStartStore.key("release", fields)  # program name
    for name in fields:
        bumped = dict(fields, **{name: "CHANGED"})
        assert k0 != WarmStartStore.key("decode", bumped), name


def test_roundtrip_and_structured_miss_reasons(tmp_path):
    store = WarmStartStore(str(tmp_path))
    fields = {"mesh": "1xcpu", "git": "abc"}
    assert store.load("decode", fields) == (None, "absent")
    assert store.save("decode", fields, b"\x01\x02payload") is True
    assert store.load("decode", fields) == (b"\x01\x02payload", "hit")
    assert store.entries() == [store.path("decode", fields)]

    # chaos hook: payload bytes flipped, header intact → digest_mismatch
    assert store.corrupt_entries() == 1
    payload, reason = store.load("decode", fields)
    assert payload is None and reason == "digest_mismatch"

    # a malformed header line is a structured miss, not a parse crash
    with open(store.path("decode", fields), "wb") as f:
        f.write(b"not json at all\n\x00\x00")
    assert store.load("decode", fields) == (None, "corrupt_header")

    # a hand-copied entry from another jaxlib is refused even when the
    # payload digest verifies (the header check is belt and braces)
    header = json.dumps({"magic": "csat-warmstart-v1", "jaxlib": "0.0.0",
                         "payload_sha256": __import__("hashlib").sha256(
                             b"pp").hexdigest()}).encode()
    with open(store.path("decode", fields), "wb") as f:
        f.write(header + b"\n" + b"pp")
    assert store.load("decode", fields) == (None, "jaxlib_mismatch")


def test_disabled_and_unwritable_stores_never_raise(tmp_path):
    off = WarmStartStore(None)
    assert not off.enabled
    assert off.load("decode", {}) == (None, "disabled")
    assert off.save("decode", {}, b"x") is False
    assert off.entries() == [] and off.corrupt_entries() == 0
    assert off.path("decode", {}) is None

    # a root that cannot be created (path under a regular file) degrades
    # to a disabled store instead of failing engine bring-up
    blocker = tmp_path / "file"
    blocker.write_text("x")
    notes = []
    broken = WarmStartStore(str(blocker / "sub"), log=notes.append)
    assert not broken.enabled
    assert any("disabled" in n for n in notes)


# ---------------------------------------------------------------------------
# engine integration: cold seed → warm hit, bit identity, corrupt fallback
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def ws_cfg(micro_config):
    """Deterministic micro config on the bit-identity paths, one prefill
    bucket (fewest programs per engine)."""
    return micro_config.replace(
        full_att=True, dropout=0.0, attention_dropout=0.0,
        cse_empty_rows="zero", serve_slots=2, bucket_src_lens=(48,),
        serve_max_rebuilds=0,
    )


@pytest.fixture(scope="module")
def stack(ws_cfg):
    from csat_tpu.train.state import create_train_state, default_optimizer, make_model

    cfg = ws_cfg
    model = make_model(cfg, SRC_V, TGT_V, TRIP_V)
    warm = collate_requests(
        [random_request_sample(cfg, SRC_V, TRIP_V, 8, seed=0)],
        cfg.max_src_len, 1, cfg, tgt_width=cfg.max_tgt_len - 1)
    params = create_train_state(
        model, default_optimizer(cfg), warm, seed=0).params
    return cfg, model, params


def _samples(cfg, n=3, seed=7):
    rng = np.random.default_rng(seed)
    return [random_request_sample(cfg, SRC_V, TRIP_V, int(ln), seed=100 + i)
            for i, ln in enumerate(rng.integers(5, cfg.max_src_len, n))]


def _tokens(reqs):
    return [np.asarray(r.tokens)[: r.n_tokens].tolist() for r in reqs]


@pytest.fixture(scope="module")
def ws_env(stack, tmp_path_factory):
    """A store seeded by one cold engine, plus that engine's outputs as
    the bit-identity reference for every warm/off/corrupt variant."""
    cfg0, model, params = stack
    root = str(tmp_path_factory.mktemp("warmstart"))
    cfg = cfg0.replace(serve_warmstart=True, serve_warmstart_dir=root)
    samples = _samples(cfg)
    eng = ServeEngine(model, params, cfg, sample_seed=0)
    reqs = eng.generate(samples)
    env = {
        "cfg": cfg, "root": root, "samples": samples,
        "ref_tokens": _tokens(reqs),
        "hits": int(eng.stats.warmstart_hits),
        "misses": int(eng.stats.warmstart_misses),
        "cold_start_s": float(eng.stats.cold_start_s),
        "entries": len(eng.warmstart.entries()),
        "events": list(eng.obs.events()),
    }
    eng.close()
    return env


def test_cold_engine_seeds_store_with_structured_misses(ws_env):
    assert ws_env["hits"] == 0
    assert ws_env["misses"] > 0  # every program missed the empty store
    assert ws_env["entries"] >= ws_env["misses"]  # each miss saved an entry
    assert ws_env["cold_start_s"] > 0
    misses = [f for _, name, _, f in ws_env["events"]
              if name == "warmstart_miss"]
    assert misses and all(m["reason"] == "absent" for m in misses)
    # bring-up provenance lands in obs for the fleet's spawn accounting;
    # it counts the ctor-time programs only — prefill buckets compile
    # lazily on first submit, so total misses can exceed it
    starts = [f for _, name, _, f in ws_env["events"]
              if name == "engine.cold_start"]
    assert starts and 0 < starts[0]["cold"] <= ws_env["misses"]
    assert starts[0]["warm"] == 0 and starts[0]["cold_start_s"] > 0


def test_warm_engine_hits_everything_bit_identically(ws_env, stack):
    _, model, params = stack
    eng = ServeEngine(model, params, ws_env["cfg"], sample_seed=0)
    reqs = eng.generate(ws_env["samples"])
    assert int(eng.stats.warmstart_misses) == 0
    assert int(eng.stats.warmstart_hits) == ws_env["misses"]
    assert any(name == "warmstart.hit" for _, name, _, _ in eng.obs.events())
    assert _tokens(reqs) == ws_env["ref_tokens"]
    # the warm-start win the :autoscale drill records
    assert float(eng.stats.cold_start_s) > 0
    eng.close()


def test_store_off_engine_is_bit_identical(ws_env, stack):
    cfg0, model, params = stack
    assert cfg0.serve_warmstart is False
    eng = ServeEngine(model, params, cfg0, sample_seed=0)
    assert eng.warmstart is None
    reqs = eng.generate(ws_env["samples"])
    assert _tokens(reqs) == ws_env["ref_tokens"]
    eng.close()


def test_corrupt_entries_fall_back_to_compile_path(ws_env, stack):
    _, model, params = stack
    store = WarmStartStore(ws_env["root"])
    n = store.corrupt_entries()
    assert n == ws_env["entries"]
    eng = ServeEngine(model, params, ws_env["cfg"], sample_seed=0)
    # every load failed its digest check, structurally, and the engine
    # compiled through the export path anyway — then re-seeded the store
    assert int(eng.stats.warmstart_hits) == 0
    assert int(eng.stats.warmstart_misses) > 0
    reasons = {f["reason"] for _, name, _, f in eng.obs.events()
               if name == "warmstart_miss"}
    assert reasons == {"digest_mismatch"}
    reqs = eng.generate(ws_env["samples"])
    assert _tokens(reqs) == ws_env["ref_tokens"]
    eng.close()
    # the compile-path fallback re-saved valid artifacts: every entry's
    # payload verifies against its header digest again
    import hashlib

    assert store.entries()
    for path in store.entries():
        with open(path, "rb") as f:
            header = json.loads(f.readline())
            payload = f.read()
        assert hashlib.sha256(payload).hexdigest() == header["payload_sha256"]

"""Operational tooling (benchmarks, corpus builders, diagnostics).

A package so shared helpers (``tools.xla_util.xla_mem``,
``tools.xla_util.cpu_child_env`` — jax-free on purpose) are importable
from ``bench.py`` and between tools — every module here also still runs
standalone via ``python tools/<name>.py``.
"""

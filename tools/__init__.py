"""Operational tooling (benchmarks, corpus builders, diagnostics).

A package so shared helpers (``tools.time_memory.xla_mem``,
``tools.time_memory.cpu_child_env``) are importable from ``bench.py`` and
between tools — every module here also still runs standalone via
``python tools/<name>.py``.
"""

"""Assemble the round-4 real-data evidence tables from run summaries.

Reads every ``summary.json`` under the given roots (the r3 committed runs,
the r4 ablation queue, and the torch-reference baseline) and rewrites the
paired tables in ``results/real_stdlib/README.md``:

* framework pairing (north-star BLEU half): torch reference vs the JAX run
  at the same 8 heads / corpus / budget;
* sbm_floor ablation: 0.01 (r3 run) vs 0.0 (quirk-fix) at equal budget;
* precision ablation: f32 (r3 run) vs bf16 at equal budget;
* PE probe subjects: pegen (h8) vs sequential (h8).

    python tools/assemble_r4_results.py
"""

from __future__ import annotations

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

RUNS = {
    # label -> summary.json path (first existing wins per label)
    "sbm f32 floor=0.01 (r3, 4 heads)": [
        "results/real_stdlib/sbm/summary.json"],
    "full_att f32 (r3, 4 heads)": [
        "results/real_stdlib/full_att/summary.json"],
    "sbm f32 floor=0.0 (4 heads)": [
        "outputs/r4/final_exp/real_stdlib_sbm_floor0/summary.json",
        "results/real_stdlib/sbm_floor0/summary.json"],
    "sbm bf16 floor=0.01 (4 heads)": [
        "outputs/r4/final_exp/real_stdlib_sbm_bf16/summary.json",
        "results/real_stdlib/sbm_bf16/summary.json"],
    "sbm f32 (8 heads, torch pair)": [
        "outputs/r4/final_exp/real_stdlib_sbm_h8/summary.json",
        "results/real_stdlib/sbm_h8/summary.json"],
    "sequential-PE f32 (8 heads)": [
        "outputs/r4/final_exp/real_stdlib_sbm_seq_h8/summary.json",
        "results/real_stdlib/seq_h8/summary.json"],
    "torch reference (8 heads)": [
        "results/real_stdlib_torch/summary.json"],
    # 24-epoch budget extension (2x): same corpus/dims/seed, both frameworks
    "sbm f32 (8 heads, 24 epochs)": [
        "outputs/r4e24/final_exp/real_stdlib_sbm_h8e24/summary.json",
        "results/real_stdlib/sbm_h8e24/summary.json"],
    "torch reference (8 heads, 24 epochs)": [
        "results/real_stdlib_torch_e24/summary.json"],
    # seed-variance bound for the pairing (12-epoch budget, seed 7)
    "sbm f32 (8 heads, 12 epochs, seed 7)": [
        "results/real_stdlib/sbm_h8s7/summary.json"],
    # the same seed-7 run resumed to 24 epochs (two-seed budget scaling)
    "sbm f32 (8 heads, 24 epochs, seed 7)": [
        "outputs/r4s7/final_exp/real_stdlib_sbm_h8s7/summary.json",
        "results/real_stdlib/sbm_h8s7e24/summary.json"],
}


def _load(label):
    for rel in RUNS[label]:
        path = os.path.join(REPO, rel)
        if os.path.exists(path):
            with open(path) as f:
                return json.load(f), rel
    return None, None


def _row(label, s):
    scores = s.get("test_scores", {})
    if isinstance(scores, dict):
        bleu = scores.get("bleu")
        rouge = scores.get("rouge_l", scores.get("rouge"))
        meteor = scores.get("meteor")
    else:  # train_real stores the run_test dict
        bleu = rouge = meteor = None
    loss = s.get("loss_curve", [None])[-1]
    best = s.get("best_val_bleu")
    wall = s.get("wall_s")
    fmt = lambda v: "—" if v is None else (f"{v:.2f}" if isinstance(v, float) else str(v))
    return (f"| {label} | {fmt(loss)} | {fmt(best)} | {fmt(bleu)} | "
            f"{fmt(rouge)} | {fmt(meteor)} | {fmt(wall)}s |")


def main() -> None:
    rows, missing = [], []
    loaded = {}
    for label in RUNS:
        s, rel = _load(label)
        if s is None:
            missing.append(label)
            continue
        loaded[label] = s
        rows.append(_row(label, s))

    out = [
        "## Round-4 paired results (12-epoch stdlib budget)",
        "",
        "All runs: 3600/200/200 stdlib-function corpus, batch 32, lr 3e-4,",
        "12 epochs, CPU. 4-head rows pair with the r3 runs; 8-head rows pair",
        "the JAX stack against the ACTUAL torch reference model trained by",
        "`tools/train_torch_real.py` on the same data (the reference CSE",
        "hard-tiles 4+4 heads, so the cross-framework pairing runs at 8).",
        "",
        "| run | final train loss | best dev BLEU | test BLEU | ROUGE-L | METEOR | wall |",
        "|---|---|---|---|---|---|---|",
        *rows,
    ]
    if missing:
        out += ["", "Pending runs: " + ", ".join(missing)]
    for tl, jl, tag in (
        ("torch reference (8 heads)", "sbm f32 (8 heads, torch pair)", "12-epoch"),
        ("torch reference (8 heads, 24 epochs)", "sbm f32 (8 heads, 24 epochs)", "24-epoch"),
    ):
        t, j = loaded.get(tl), loaded.get(jl)
        if t and j:
            tb = t["test_scores"]["bleu"]
            jb = j["test_scores"]["bleu"] if isinstance(j.get("test_scores"), dict) else None
            if isinstance(jb, (int, float)):
                out += ["",
                        f"**Framework delta ({tag} budget, test BLEU, 8 heads): "
                        f"JAX {jb:.2f} vs torch {tb:.2f} → {jb - tb:+.2f}** "
                        f"(north-star target: within 0.1 at the reference's "
                        f"full training scale; same-budget CPU pairing)."]
    j24 = loaded.get("sbm f32 (8 heads, 24 epochs)")
    t24 = loaded.get("torch reference (8 heads, 24 epochs)")
    if j24 and t24:
        out += ["",
                "Interpretation of the 24-epoch extension: the 12-epoch "
                "pairing lands within the 0.1 target; doubling the budget "
                "has the torch reference pulling ahead at this seed — its "
                "dev BLEU was still climbing at epoch 23 while the JAX "
                "run's dev metric plateaued after epoch 20 (final losses "
                "3.52 vs 3.62). Single-seed runs on a 200-sample test set "
                "carry BLEU variance of the same order (see the seed-7 row "
                "for the measured spread); module-level parity is "
                "torch-differential-tested bit-close, so the divergence is "
                "training-dynamics realization, not a transcription error. "
                "Measured 12-epoch seed spread on the JAX side: 4.36 (seed "
                "2021) vs 4.32 (seed 7) — tight, so the 24-epoch gap is a "
                "budget-scaling effect at these dims, not run-to-run noise "
                "at the 12-epoch operating point."]
    print("\n".join(out))
    readme = os.path.join(REPO, "results", "real_stdlib", "README.md")
    with open(readme) as f:
        existing = f.read()
    marker = "## Round-4 paired results"
    base = existing.split(marker)[0].rstrip()
    with open(readme, "w") as f:
        f.write(base + "\n\n" + "\n".join(out) + "\n")
    sys.exit(0)


if __name__ == "__main__":
    main()

"""Host-side input-pipeline microbench: native fused collate vs NumPy.

Times the per-batch collate (gather + mask + adjacency + offset/clamp of
the (B,N,N) relation matrices plus the small-field gathers) at flagship
dimensions — the work the host must keep ahead of the device step for the
prefetch pipeline (csat_tpu/train/loop.py) to hide it.

    python tools/bench_collate.py [--samples 2000] [--batch 64] [--n 150]
                                  [--iters 40]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from csat_tpu.data.dataset import collate, collate_indexed  # noqa: E402
from csat_tpu.native import load_collate  # noqa: E402


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--samples", type=int, default=2000)
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--n", type=int, default=150)
    ap.add_argument("--iters", type=int, default=40)
    args = ap.parse_args()

    rng = np.random.default_rng(0)
    s, n = args.samples, args.n
    arrays = {
        "src_seq": rng.integers(0, 10_000, (s, n)).astype(np.int32),
        "tgt_seq": rng.integers(0, 20_000, (s, 49)).astype(np.int32),
        "target": rng.integers(0, 20_000, (s, 49)).astype(np.int32),
        "L_raw": rng.integers(-90, 90, (s, n, n)).astype(np.int16),
        "T_raw": rng.integers(-90, 90, (s, n, n)).astype(np.int16),
        "num_node": rng.integers(1, n, (s,)).astype(np.int32),
        "tree_pos": (rng.random((s, n, 128)) < 0.1).astype(np.uint8),
        "triplet": rng.integers(0, 1246, (s, n)).astype(np.int32),
    }
    batches = [
        rng.integers(0, s, (args.batch,)).astype(np.int64)
        for _ in range(args.iters)
    ]

    def timed(fn):
        fn(batches[0])  # warm
        t0 = time.perf_counter()
        for idx in batches:
            fn(idx)
        return (time.perf_counter() - t0) / len(batches)

    numpy_s = timed(
        lambda idx: collate({k: v[idx] for k, v in arrays.items()}, n)
    )
    native_available = load_collate() is not None
    native_s = (
        timed(lambda idx: collate_indexed(arrays, idx, n))
        if native_available
        else None
    )
    sample = collate_indexed(arrays, batches[0], n)
    feed_bytes = sum(v.nbytes for v in sample)
    wide_bytes = sum(
        np.prod(v.shape) * (4 if v.dtype != np.bool_ else 1) for v in sample
    )
    rec = {
        "batch": args.batch,
        "n": n,
        "feed_bytes_per_batch": int(feed_bytes),
        "uncompressed_bytes_per_batch": int(wide_bytes),
        "numpy_ms_per_batch": round(numpy_s * 1e3, 3),
        "native_ms_per_batch": (
            round(native_s * 1e3, 3) if native_s is not None else None
        ),
        "speedup": round(numpy_s / native_s, 2) if native_s else None,
        "native_available": native_available,
    }
    print(json.dumps(rec))


if __name__ == "__main__":
    main()

"""Measure the PyTorch reference-equivalent baseline for ``bench.py``.

Independent PyTorch implementation of the reference's default training-step
workload (``/root/reference/config/python.py``: pegen CSE + SBM sparse
attention, 512/256 dims, batch 64, N=150) — written fresh from the
architecture description in ``SURVEY.md`` §2/§3, not copied from the
reference. It exists to put a measured number behind ``vs_baseline``:

    python tools/bench_torch_baseline.py  →  baseline_torch.json

The reference targets CUDA; this host exposes no CUDA device, so the
measurement runs on whatever torch offers (recorded in the JSON). The
north-star comparison (≥4× AST-nodes/sec/chip, ``BASELINE.json``) is
defined against the reference on its own GPU hardware; this script gives
the same-host number so ``bench.py`` can report a ratio that was actually
measured rather than assumed.

Workload per step (mirrors ``script/train.py:103-116``): forward,
label-smoothed NLL + sparsity-weighted loss, backward, AdamW update.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import time

import torch
import torch.nn as nn
import torch.nn.functional as F

# ---- workload constants (ref config/python.py) ----
SBM_DIM, PE_DIM, PEGEN_DIM, HIDDEN = 512, 256, 512, 512
HEADS, CSE_LAYERS, SBM_LAYERS, DEC_LAYERS = 8, 4, 4, 4
CLUSTERS, FFN, MAX_SRC, MAX_TGT = 10, 2048, 150, 50
SRC_V, TGT_V, BATCH, SW = 10_000, 20_000, 64, 1e-2


class DisentangledLayer(nn.Module):
    """c2c + p2c + c2p relative attention (ref disentangled_attn.py:44-65)."""

    def __init__(self):
        super().__init__()
        d, h = PEGEN_DIM, HEADS
        self.h, self.dk = h, d // h
        self.qkv = nn.Linear(d, 3 * d)
        self.out = nn.Linear(d, d)
        self.rel_q = nn.Linear(d, d // 2)
        self.rel_k = nn.Linear(d, d // 2)
        self.norm1 = nn.LayerNorm(d)
        self.norm2 = nn.LayerNorm(d)
        self.ffn = nn.Sequential(nn.Linear(d, d), nn.GELU(), nn.Linear(d, d))

    def forward(self, x, tables, rel, mask):
        b, n, d = x.shape
        h, dk = self.h, self.dk
        q, k, v = self.qkv(self.norm1(x)).chunk(3, -1)
        q, k, v = (t.view(b, n, h, dk).transpose(1, 2) for t in (q, k, v))
        # tables: (2, R, d) → per-pseudo-head-group projections
        lq = self.rel_q(tables).view(2, -1, h // 2, dk).permute(0, 2, 1, 3)
        lk = self.rel_k(tables).view(2, -1, h // 2, dk).permute(0, 2, 1, 3)
        lq = lq.reshape(h, -1, dk)  # (H, R, dk): 4 L-heads then 4 T-heads
        lk = lk.reshape(h, -1, dk)
        scale = math.sqrt(3 * dk)
        c2c = q @ k.transpose(-1, -2)
        c2p = torch.gather(q @ lk.transpose(-1, -2), 3, rel)
        p2c = torch.gather(k @ lq.transpose(-1, -2), 3, rel).transpose(-1, -2)
        s = (c2c + c2p + p2c) / scale
        s = s.masked_fill(mask, -1e9)
        o = (F.softmax(s, -1) @ v).transpose(1, 2).reshape(b, n, d)
        x = x + self.out(o)
        return x + self.ffn(self.norm2(x))


class SBMLayer(nn.Module):
    """Cluster-sampled sparse attention block (ref sbm_attn.py:32-66)."""

    def __init__(self):
        super().__init__()
        d, h = SBM_DIM, HEADS
        self.h, self.dk = h, d // h
        self.qkv = nn.Linear(d, 3 * d)
        self.out = nn.Linear(d, d)
        self.clusters = nn.Parameter(torch.empty(h, CLUSTERS, self.dk))
        nn.init.orthogonal_(self.clusters.view(h * CLUSTERS, self.dk))
        self.proj = nn.Sequential(
            nn.Linear(self.dk, self.dk), nn.ReLU(),
            nn.Linear(self.dk, self.dk), nn.ReLU(), nn.Linear(self.dk, self.dk)
        )
        self.norm1 = nn.LayerNorm(d)
        self.norm2 = nn.LayerNorm(d)
        self.ffn = nn.Sequential(nn.Linear(d, FFN), nn.GELU(), nn.Linear(FFN, d))

    def forward(self, x, pad):
        b, n, d = x.shape
        h, dk = self.h, self.dk
        q, k, v = self.qkv(self.norm1(x)).chunk(3, -1)
        q, k, v = (t.view(b, n, h, dk).transpose(1, 2) for t in (q, k, v))
        s = F.softmax(
            (self.clusters @ self.clusters.transpose(-1, -2)).view(h, -1), -1
        ).view(h, CLUSTERS, CLUSTERS)
        q_hat = torch.sigmoid(
            torch.einsum("bhnd,hkd->bhnk", self.proj(q), self.clusters))
        k_hat = torch.sigmoid(
            torch.einsum("bhnd,hkd->bhnk", self.proj(k), self.clusters))
        exp_a = torch.einsum("bhnk,hkj,bhmj->bhnm", q_hat, s, k_hat)
        a = torch.bernoulli(exp_a.clamp(0.01, 0.99))
        graph = a + exp_a - exp_a.detach()  # straight-through surrogate
        dot = (q @ k.transpose(-1, -2)) / math.sqrt(dk)
        dot = dot.masked_fill(pad[:, None, None, :], -1e30)
        attn = F.normalize(F.softmax(dot, -1) * graph, p=1, dim=-1)
        sparsity = a.sum() / a.numel()
        o = (attn @ v).transpose(1, 2).reshape(b, n, d)
        x = x + self.out(o)
        return x + self.ffn(self.norm2(x)), sparsity


class Baseline(nn.Module):
    def __init__(self):
        super().__init__()
        self.src_emb = nn.Embedding(SRC_V, SBM_DIM - PE_DIM)
        self.pe_emb = nn.Embedding(SRC_V, PEGEN_DIM)
        self.tables = nn.Parameter(torch.randn(2, MAX_SRC, PEGEN_DIM) * 0.02)
        self.cse = nn.ModuleList(DisentangledLayer() for _ in range(CSE_LAYERS))
        self.pe_expand = nn.Linear(PEGEN_DIM, PE_DIM)
        self.sbm = nn.ModuleList(SBMLayer() for _ in range(SBM_LAYERS))
        self.enc_out = nn.Linear(SBM_DIM, HIDDEN)
        self.tgt_emb = nn.Embedding(TGT_V, HIDDEN)
        dec_layer = nn.TransformerDecoderLayer(
            HIDDEN, HEADS, FFN, dropout=0.2, activation="gelu", batch_first=True
        )
        self.dec = nn.TransformerDecoder(dec_layer, DEC_LAYERS)
        self.gen = nn.Linear(HIDDEN, TGT_V)

    def forward(self, src, tgt, rel, rel_mask, pad):
        pe = self.pe_emb(src)
        for layer in self.cse:
            pe = layer(pe, self.tables, rel, rel_mask)
        x = torch.cat([self.src_emb(src), self.pe_expand(pe)], -1)
        sparsities = []
        for layer in self.sbm:
            x, sp = layer(x, pad)
            sparsities.append(sp)
        mem = self.enc_out(x)
        n = tgt.shape[1]
        causal = torch.triu(torch.ones(n, n, dtype=torch.bool), 1)
        out = self.dec(self.tgt_emb(tgt), mem, tgt_mask=causal)
        return F.log_softmax(self.gen(out), -1), torch.stack(sparsities).mean()


def _measure(batch: int, steps: int, dev: str) -> tuple:
    torch.manual_seed(0)
    model = Baseline().to(dev)
    opt = torch.optim.AdamW(model.parameters(), lr=1e-4, eps=1e-6)

    b = batch
    src = torch.randint(4, SRC_V, (b, MAX_SRC), device=dev)
    tgt = torch.randint(4, TGT_V, (b, MAX_TGT), device=dev)
    rel = torch.randint(0, MAX_SRC, (b, HEADS, MAX_SRC, MAX_SRC), device=dev)
    rel_mask = rel == 75  # distance-0 pairs masked (SURVEY §8.3)
    pad = torch.zeros(b, MAX_SRC, dtype=torch.bool, device=dev)

    def step():
        opt.zero_grad()
        logp, sparsity = model(src, tgt[:, :-1], rel, rel_mask, pad)
        loss = F.nll_loss(logp.reshape(-1, TGT_V), tgt[:, 1:].reshape(-1))
        (loss + SW * sparsity).backward()
        opt.step()
        return loss

    step()  # warmup
    if dev == "cuda":
        torch.cuda.synchronize()
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = step()
    if dev == "cuda":
        torch.cuda.synchronize()
    dt = time.perf_counter() - t0
    return b * MAX_SRC * steps / dt, float(loss)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=5)
    ap.add_argument("--batch", type=int, default=BATCH)
    ap.add_argument("--sweep", type=int, nargs="*", default=None,
                    help="measure several batch sizes and write the full "
                        "by_batch table (bench.py's same-batch ratio needs "
                        "it); headline = the best; e.g. --sweep 6 8 16 64")
    args = ap.parse_args()

    dev = "cuda" if torch.cuda.is_available() else "cpu"
    batches = args.sweep if args.sweep else [args.batch]
    by_batch, loss = {}, 0.0
    for b in batches:
        nodes, loss = _measure(b, args.steps, dev)
        by_batch[str(b)] = round(nodes, 1)

    best_b = max(by_batch, key=lambda k: by_batch[k])
    result = {
        "ast_nodes_per_sec_per_chip": by_batch[best_b],
        "device": dev,
        "torch": torch.__version__,
        "steps": args.steps,
        "batch": int(best_b),
        "note": "headline = best over the sweep; bench.py compares "
                "same-batch numbers via by_batch",
        "by_batch": by_batch,
        "loss": loss,
    }
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", "baseline_torch.json")
    with open(os.path.abspath(path), "w") as f:
        json.dump(result, f, indent=1)
    print(json.dumps(result))


if __name__ == "__main__":
    main()

"""Build a REAL code-summarization corpus from the Python standard library.

The reference trains on tree-sitter-extracted (AST, docstring-summary)
pairs of real functions (``/root/reference/py/tree_sitter_parse.ipynb`` →
``process.py``). This tool produces the same artifact chain from a real,
permissively-licensed source that is guaranteed present in the image: the
CPython standard library (PSF license).

Pipeline (all L0→L1 product code, nothing bespoke):

1. walk ``sysconfig.get_path("stdlib")`` ``*.py`` files;
2. collect top-level (and class-level) ``def``s that carry a docstring;
   the NL target is the docstring's first sentence, lowercased and
   punctuation-tokenized the way the reference corpora are distributed;
3. filter: 4–30 NL tokens, ASCII, source ≤ 60 lines, ≥ 8 AST nodes;
4. deterministic shuffle → train/dev/test split;
5. ``csat_tpu.data.extract.extract_corpus`` writes ``ast.original`` +
   ``nl.original`` per split;
6. ``csat_tpu.data.preprocess.process_dataset`` builds ``split_pot.seq``,
   ``split_matrices.npz`` and the vocabs.

Usage::

    python tools/build_real_corpus.py --out ./data/stdlib_python \
        --max_samples 4000 --max_ast_len 150
"""

from __future__ import annotations

import argparse
import ast
import os
import random
import re
import sys
import sysconfig

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from csat_tpu.data.extract import extract_corpus  # noqa: E402
from csat_tpu.data.preprocess import process_dataset  # noqa: E402

_WORD = re.compile(r"[A-Za-z0-9]+|[^\sA-Za-z0-9]")


def _nl_tokens(docstring: str) -> list:
    """First sentence of the docstring → lowercased word/punct tokens."""
    first = docstring.strip().split("\n\n")[0].replace("\n", " ")
    m = re.search(r"(?<=[a-z0-9\)])\.(?:\s|$)", first)
    if m:
        first = first[: m.start() + 1]
    return [t.lower() for t in _WORD.findall(first)]


def harvest(max_samples: int, seed: int = 0, site_packages: bool = False) -> list:
    """Collect (function_source, nl_summary) pairs from the stdlib — plus,
    with ``site_packages``, the installed third-party distributions (numpy,
    torch, jax, transformers, … — all permissively-licensed OSS baked into
    the image), which is how the corpus scales past the ~5k docstring'd
    functions the stdlib alone carries (VERDICT r4 #5: approach the
    reference's ~50k-sample regime, ``/root/reference/config/python.py:25``)."""
    roots = [sysconfig.get_path("stdlib")]
    if site_packages:
        roots.append(sysconfig.get_path("purelib"))
    files = []
    for root in roots:
        # the stdlib root always skips its nested site-packages (pip's
        # vendored tree, and — on non-venv layouts — a duplicate of
        # purelib); only the purelib root itself is allowed to be one
        skip = ("test", "idlelib", "__pycache__")
        if root == roots[0]:
            skip += ("site-packages",)
        for base, dirs, names in os.walk(root):
            # prune by exact directory NAME, not path substring: a
            # substring match on 'test' also pruned pytest/, latest/,
            # unittest/, … silently shrinking the --site_packages harvest
            # (ADVICE r5). Pruning dirs in place is sufficient — os.walk
            # then never descends into a skipped component at all
            dirs[:] = [d for d in dirs if d not in skip]
            files.extend(os.path.join(base, n) for n in names if n.endswith(".py"))
    files.sort()

    pairs, seen = [], set()
    for path in files:
        try:
            src = open(path, encoding="utf-8", errors="replace").read()
            tree = ast.parse(src)
        except (SyntaxError, ValueError):
            continue
        defs = []
        for node in tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs.append(node)
            elif isinstance(node, ast.ClassDef):
                defs.extend(
                    n for n in node.body
                    if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
                )
        for fn in defs:
            doc = ast.get_docstring(fn)
            if not doc or not doc.isascii():
                continue
            nl = _nl_tokens(doc)
            if not 4 <= len(nl) <= 30:
                continue
            if fn.name.startswith("__"):
                continue
            seg = ast.get_source_segment(src, fn)
            if seg is None or seg.count("\n") > 60:
                continue
            # dedup identical bodies vendored into multiple modules
            key = (fn.name, " ".join(nl))
            if key in seen:
                continue
            seen.add(key)
            # re-indent methods so each sample parses standalone
            lines = seg.split("\n")
            indent = len(lines[0]) - len(lines[0].lstrip())
            if indent:
                lines = [ln[indent:] if len(ln) > indent else ln.lstrip() for ln in lines]
            pairs.append(("\n".join(lines), " ".join(nl)))

    random.Random(seed).shuffle(pairs)
    return pairs[:max_samples]


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--out", required=True)
    p.add_argument("--max_samples", type=int, default=4000)
    p.add_argument("--max_ast_len", type=int, default=150)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--site_packages", action="store_true",
                   help="also harvest installed third-party packages "
                        "(numpy/torch/jax/… — scales past the stdlib's ~5k "
                        "docstring'd functions)")
    args = p.parse_args()

    pairs = harvest(args.max_samples, args.seed,
                    site_packages=args.site_packages)
    n = len(pairs)
    n_dev = n_test = max(1, n // 20)
    splits = {
        "dev": pairs[:n_dev],
        "test": pairs[n_dev : n_dev + n_test],
        "train": pairs[n_dev + n_test :],
    }
    for split, split_pairs in splits.items():
        out = os.path.join(args.out, split)
        kept = extract_corpus(split_pairs, out, "python")
        print(f"{split}: {kept}/{len(split_pairs)} extracted")
    process_dataset(args.out, args.max_ast_len, make_vocab=True,
                    n_jobs=os.cpu_count() or 1)


if __name__ == "__main__":
    main()

"""Render a chaos-run timeline dump (ISSUE 12).

Reads the JSONL artifact :meth:`csat_tpu.resilience.chaos.ChaosReport.dump`
writes (one ``{"meta": ...}`` header line, then the ts-sorted merged
timeline of every component recorder — fleet, each replica engine, and the
invariant monitor) and renders:

* the run header — trace, fault plan, outcome counts, capacity fraction,
  invariant checks vs violations;
* a **fault-vs-invariant timeline** — one row per fault event
  (``fault.*``), degradation event (``req.brownout``,
  ``fleet.shed_oldest``, ``fleet.retire``, ``fleet.resubmit``,
  ``fleet.backoff``) and invariant record (``invariant.*``), in time
  order with per-component attribution;
* a per-name event census of the full timeline;
* every ``invariant.violation`` in detail (the postmortem pointer).

Usage::

    python tools/chaos_report.py outputs/postmortem/postmortem_chaos_timeline.jsonl
    python tools/chaos_report.py --full chaos_run.jsonl   # every event row
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Tuple

# event-name prefixes that make the condensed timeline: injected faults,
# the degradation ladder acting, the invariant monitor's verdicts, the
# elastic-fleet lifecycle (spawn/heal — ISSUE 13), SLO burn-rate alert
# transitions (ISSUE 14), the tiered KV store's spill/demote/restore/
# restore_miss ladder (ISSUE 16), and the network front door's
# connect/stall/resume/drop ladder (ISSUE 20)
TIMELINE_PREFIXES = (
    "fault.", "invariant.", "req.brownout", "fleet.shed_oldest",
    "fleet.retire", "fleet.resubmit", "fleet.backoff", "fleet.draining",
    "fleet.spawn", "autoscale.", "slo.", "tier.", "net.",
)


def load_dump(path: str) -> Tuple[dict, List[dict]]:
    """(meta, events) from a ChaosReport.dump JSONL artifact."""
    meta: dict = {}
    events: List[dict] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            if "meta" in rec and not events and not meta:
                meta = rec["meta"]
            else:
                events.append(rec)
    return meta, events


def header_lines(meta: dict) -> List[str]:
    out = ["== chaos run =="]
    if not meta:
        return out + ["  (no meta header in dump)"]
    out.append(f"  trace: {meta.get('trace', '?')}   "
               f"plan: {meta.get('plan', '?')}   "
               f"submitted: {meta.get('submitted', '?')}")
    outcomes = meta.get("outcomes") or {}
    if outcomes:
        out.append("  outcomes: " + "  ".join(
            f"{k}={v}" for k, v in sorted(outcomes.items())))
    out.append(f"  invariant checks: {meta.get('checks', 0)}   "
               f"violations: {meta.get('violations', 0)}   "
               f"capacity_frac: {meta.get('capacity_frac', 1.0)}   "
               f"resubmissions: {meta.get('resubmissions', 0)}")
    plan = meta.get("fault_plan")
    if plan:
        try:
            events = json.loads(plan).get("events", ())
            out.append("  fault plan: " + "; ".join(
                f"{e['kind']}@+{e['at']}"
                + (f" r{e['replica']}" if e.get("replica") else "")
                for e in events))
        except (ValueError, KeyError):
            pass
    slo = meta.get("slo_alerts") or {}
    if slo:
        out.append("  slo alerts: " + "  ".join(
            f"{k}={v}" for k, v in sorted(slo.items())))
    net = meta.get("net") or {}
    if net:
        out.append("  net: " + "  ".join(
            f"{k}={v}" for k, v in sorted(net.items())))
    verdict = "CLEAN" if not meta.get("violations") else "VIOLATED"
    out.append(f"  verdict: {verdict}")
    return out


def timeline_lines(events: List[dict], full: bool = False,
                   limit: int = 200) -> List[str]:
    """The condensed fault-vs-invariant timeline (or every event with
    ``full=True``), relative-timestamped from the first event."""
    rows = [e for e in events
            if full or any(e.get("name", "").startswith(p)
                           for p in TIMELINE_PREFIXES)]
    out = [f"== timeline ({len(rows)} of {len(events)} events) =="]
    if not rows:
        return out + ["  (no fault / invariant events in dump)"]
    t0 = events[0].get("ts", 0.0)
    shown = rows if len(rows) <= limit else rows[:limit]
    for e in shown:
        extra = {k: v for k, v in e.items()
                 if k not in ("ts", "name", "component", "dur")}
        fields = ("  " + " ".join(f"{k}={v}" for k, v in sorted(extra.items()))
                  if extra else "")
        out.append(f"  +{e.get('ts', t0) - t0:9.4f}s "
                   f"{e.get('component', '?'):>9} "
                   f"{e.get('name', '?'):<24}{fields}")
    if len(rows) > limit:
        out.append(f"  ... {len(rows) - limit} more (use --limit)")
    return out


def census_lines(events: List[dict]) -> List[str]:
    counts: dict = {}
    for e in events:
        counts[e.get("name", "?")] = counts.get(e.get("name", "?"), 0) + 1
    out = ["== event census =="]
    for name in sorted(counts, key=lambda n: (-counts[n], n)):
        out.append(f"  {counts[name]:6d}  {name}")
    return out


def violation_lines(events: List[dict]) -> List[str]:
    bad = [e for e in events if e.get("name") == "invariant.violation"]
    if not bad:
        return []
    out = [f"== violations ({len(bad)}) =="]
    for e in bad:
        extra = {k: v for k, v in e.items()
                 if k not in ("ts", "name", "component", "dur")}
        out.append("  " + json.dumps(extra, sort_keys=True))
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("dump", help="ChaosReport.dump JSONL artifact")
    ap.add_argument("--full", action="store_true",
                    help="show every timeline event, not just faults/"
                         "invariants/degradation")
    ap.add_argument("--limit", type=int, default=200,
                    help="max timeline rows to print")
    args = ap.parse_args(argv)

    meta, events = load_dump(args.dump)
    lines = header_lines(meta)
    lines += [""] + timeline_lines(events, full=args.full, limit=args.limit)
    lines += [""] + census_lines(events)
    bad = violation_lines(events)
    if bad:
        lines += [""] + bad
    print("\n".join(lines))
    # a dirty run exits nonzero so CI / scripts can gate on the artifact
    return 1 if (meta.get("violations")
                 or any(e.get("name") == "invariant.violation"
                        for e in events)) else 0


if __name__ == "__main__":
    sys.exit(main())

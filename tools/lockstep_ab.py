"""Deterministic lockstep A/B: JAX stack vs torch reference, same init.

VERDICT r4 #2 root-cause harness for the 24-epoch BLEU gap. The module
parity tests prove single-forward agreement; this tool proves (or refutes)
*whole-training-step* agreement over hundreds of optimizer steps:

* the torch reference model (imported from ``/root/reference`` at runtime,
  nothing copied) is built at the paired dims and its *initial* state_dict
  is ported to flax params with the same converters the parity tests use
  (``tests/test_reference_parity.py:111-222``);
* both frameworks run in no-dropout mode (torch ``.eval()``, flax
  ``deterministic=True`` — the reference hardcodes several 0.2 dropouts
  that a ``dropout=0`` constructor arg does not reach, so eval mode is the
  only way to switch them all off);
* the STE Bernoulli draw is the one remaining stochastic op; both sides
  are patched to consume the SAME uniform noise per (step, layer) —
  torch via ``torch.bernoulli`` monkeypatch (the parity tests' trick),
  flax by threading the noise arrays through the jitted step as real
  arguments (trace-time pop binds each ``bernoulli_noise`` call site to an
  argument position);
* both sides take AdamW(correct_bias=False, lr) steps on the same batch
  sequence (same shuffle seeds as the real paired runs).

Output: per-step |Δloss|, plus a final per-tensor drift table (torch
params converted to the flax tree and diffed leaf-by-leaf) that localizes
any divergence to the first op whose gradient disagrees.

    python tools/lockstep_ab.py --data_dir ./data/stdlib_python --steps 150
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _load_parity_helpers():
    spec = importlib.util.spec_from_file_location(
        "parity_helpers", os.path.join(REPO, "tests", "test_reference_parity.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--data_dir", required=True)
    p.add_argument("--steps", type=int, default=150)
    p.add_argument("--batch_size", type=int, default=32)
    p.add_argument("--learning_rate", type=float, default=3e-4)
    p.add_argument("--out", default="./results/lockstep")
    p.add_argument("--variant", choices=["sbm", "full_att"], default="sbm")
    p.add_argument("--zero_pad", action="store_true",
                   help="zero the torch PAD embedding rows at init so both "
                        "frameworks compute the same function (isolates the "
                        "frozen-garbage-PAD-row quirk, tools/step0_probe.py)")
    args = p.parse_args()

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import torch

    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from tools.train_torch_real import _import_reference

    ref_module, ref_utils, ref_optimizer = _import_reference()
    ph = _load_parity_helpers()  # torch→flax converters (plain functions)

    from csat_tpu.configs import get_config
    from csat_tpu.data.dataset import ASTDataset, iterate_batches
    from csat_tpu.data.vocab import load_vocab
    from csat_tpu.train.loss import label_smoothing_loss
    from csat_tpu.train.optimizer import adamw
    from csat_tpu.train.state import make_model

    full_att = args.variant == "full_att"
    name = "python_full_att" if full_att else "python"
    cfg = get_config(
        name, data_dir=args.data_dir, batch_size=args.batch_size,
        pe_dim=64, pegen_dim=128, sbm_enc_dim=128, hidden_size=128,
        num_heads=8, num_layers=2, sbm_layers=2, clusters=(8, 8),
        dim_feed_forward=512, max_tgt_len=30,
    )
    src_vocab, tgt_vocab = load_vocab(cfg.data_dir)
    train_ds = ASTDataset(cfg, "train", src_vocab, tgt_vocab)

    torch.manual_seed(cfg.seed)
    tmodel = ref_module.csa_trans.CSATrans(
        src_vocab_size=src_vocab.size(), tgt_vocab_size=tgt_vocab.size(),
        hidden_size=cfg.hidden_size, num_heads=cfg.num_heads,
        num_layers=cfg.num_layers, sbm_layers=cfg.sbm_layers,
        use_pegen="pegen", dim_feed_forward=cfg.dim_feed_forward,
        dropout=cfg.dropout, pe_dim=cfg.pe_dim, pegen_dim=cfg.pegen_dim,
        sbm_enc_dim=cfg.sbm_enc_dim, clusters=list(cfg.clusters),
        full_att=full_att, max_src_len=cfg.max_src_len,
    )
    tmodel.eval()  # all dropouts off; STE still samples (forward is ungated)
    if args.zero_pad:
        with torch.no_grad():
            for emb in (tmodel.src_embedding, tmodel.src_pe_embedding,
                        tmodel.tgt_embedding):
                emb.word_embeddings.weight[0].zero_()

    def full_params(sd):
        pp = {
            "src_embedding": ph._emb(sd, "src_embedding"),
            "tgt_embedding": ph._emb(sd, "tgt_embedding"),
            "src_pe_embedding": ph._emb(sd, "src_pe_embedding"),
            "pegen": ph.cse_params(sd, cfg.num_layers),
            "encoder": ph.sbm_params(sd, cfg.sbm_layers, full_att=full_att),
            "decoder": ph.decoder_params(sd, cfg.decoder_layers, cfg.hidden_size),
            "generator": {"Dense_0": ph._lin(sd, "generator.linear")},
        }
        return pp

    # force real copies: t2n returns views over torch's live storage, and
    # CPU jnp.asarray can be zero-copy — without the copy the "initial" JAX
    # params would silently track torch's in-place optimizer updates
    params = jax.tree.map(lambda a: jnp.array(np.array(a, copy=True)),
                          full_params(tmodel.state_dict()))
    fmodel = make_model(cfg, src_vocab.size(), tgt_vocab.size())

    tx = adamw(args.learning_rate, correct_bias=False)
    opt_state = tx.init(params)
    topt = ref_optimizer.AdamW(
        tmodel.parameters(), lr=args.learning_rate, correct_bias=False)
    criterion = ref_utils.label_smooth.LabelSmoothing(
        padding_idx=0, smoothing=cfg.smoothing)

    # ---- shared-noise plumbing -------------------------------------------
    b, h, n = cfg.batch_size, cfg.num_heads, cfg.max_src_len
    n_draws = 0 if full_att else cfg.sbm_layers
    noise_rng = np.random.default_rng(123)

    # flax: bernoulli_noise pops the jitted step's noise *tracers* at trace
    # time, turning each call site into a real function argument
    import csat_tpu.models.sbm as sbm_mod

    _override = []
    sbm_mod.bernoulli_noise = lambda key, shape: _override.pop(0)

    # torch: same values via the parity tests' bernoulli monkeypatch
    _tnoise = []
    torch.bernoulli = lambda t: (torch.from_numpy(_tnoise.pop(0)) < t).float()

    def loss_fn(params, batch, noises):
        _override[:] = list(noises)
        log_probs, sparsity, _, _, _ = fmodel.apply(
            {"params": params}, batch, deterministic=True,
            rngs={"sample": jax.random.key(0)},
        )
        nll = label_smoothing_loss(log_probs, batch.target, cfg.smoothing)
        return nll + cfg.sw * sparsity, nll

    import functools

    import optax

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def jstep(params, opt_state, batch, noises):
        (total, nll), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params, batch, noises)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, total, nll

    def to_torch(batch):
        import types as _t

        d = _t.SimpleNamespace()
        for f in ("src_seq", "tgt_seq", "L", "T", "num_node", "triplet"):
            setattr(d, f, torch.from_numpy(np.asarray(getattr(batch, f))).long())
        for f in ("L_mask", "T_mask", "adj", "tree_pos"):
            setattr(d, f, torch.from_numpy(np.asarray(getattr(batch, f))))
        return d, torch.from_numpy(np.asarray(batch.target)).long()

    os.makedirs(args.out, exist_ok=True)
    rec = {"steps": [], "dims": {"b": b, "h": h, "n": n}, "variant": args.variant}
    step = 0
    epoch = 0
    t0 = time.monotonic()
    done = False
    while not done:
        for batch in iterate_batches(train_ds, cfg.batch_size, shuffle=True,
                                     seed=cfg.seed + 1 + epoch):
            noises = [noise_rng.uniform(size=(b, h, n, n)).astype(np.float32)
                      for _ in range(n_draws)]
            # torch side first (it mutates _tnoise)
            _tnoise[:] = [x.copy() for x in noises]
            d, target = to_torch(batch)
            out, tsp, _, _, _ = tmodel(d)
            tnll = criterion(out.reshape(-1, out.size(-1)), target.reshape(-1))
            tloss = tnll + cfg.sw * tsp
            topt.zero_grad()
            tloss.backward()
            topt.step()

            params, opt_state, jtotal, jnll = jstep(
                params, opt_state, batch, [jnp.asarray(x) for x in noises])
            jt, tt = float(jtotal), float(tloss.detach())
            rec["steps"].append(
                {"step": step, "jax": round(jt, 6), "torch": round(tt, 6),
                 "adiff": round(abs(jt - tt), 6)})
            if step % 10 == 0:
                print(f"step {step}: jax {jt:.5f} torch {tt:.5f} "
                      f"|Δ| {abs(jt - tt):.2e} ({time.monotonic() - t0:.0f}s)",
                      flush=True)
            step += 1
            if step >= args.steps:
                done = True
                break
        epoch += 1

    # ---- final per-tensor drift table ------------------------------------
    tparams = jax.tree.map(jnp.asarray, full_params(tmodel.state_dict()))
    flat_j = jax.tree_util.tree_flatten_with_path(params)[0]
    flat_t = jax.tree_util.tree_flatten_with_path(tparams)[0]
    drift = []
    for (pj, vj), (pt, vt) in zip(flat_j, flat_t):
        name = "/".join(str(getattr(k, "key", k)) for k in pj)
        denom = float(jnp.max(jnp.abs(vt))) or 1.0
        drift.append((name, float(jnp.max(jnp.abs(vj - vt))) / denom))
    drift.sort(key=lambda kv: -kv[1])
    rec["param_drift_top"] = [
        {"tensor": k, "max_rel_diff": round(v, 8)} for k, v in drift[:15]]
    rec["param_drift_median"] = float(np.median([v for _, v in drift]))
    rec["wall_s"] = round(time.monotonic() - t0, 1)
    tag = f"{args.variant}_zp" if args.zero_pad else args.variant
    rec["zero_pad"] = args.zero_pad
    with open(os.path.join(args.out, f"lockstep_{tag}.json"), "w") as f:
        json.dump(rec, f, indent=1)
    last = rec["steps"][-1]
    print(json.dumps({"final_adiff": last["adiff"],
                      "median_drift": rec["param_drift_median"],
                      "top_drift": rec["param_drift_top"][:3]}))


if __name__ == "__main__":
    main()

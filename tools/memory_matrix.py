"""Step-time / peak-memory matrix over (N, remat, kernel backend).

Runs ``tools/time_memory.py`` once per combination in a fresh process (so
peak-RSS and live-buffer readings do not bleed across combos) and writes a
JSONL + a compact summary table. This is the evidence artifact for the
long-AST memory story (VERDICT r3 "what's missing" #3): remat on/off and
flash-vs-fused-vs-XLA at N=150 vs N=512.

Presets:

* ``--device cpu`` (default): XLA-backend combos only, small batch — the
  pallas kernels only *interpret* on CPU, so their CPU step time / memory
  is not evidence of anything; and CPU has no device memory stats, so the
  recorded bounds are live-buffer floors + host-RSS ceilings.
* ``--device tpu``: full matrix incl. pallas flash (counter noise) and
  fused (shared noise) at the reference batch 64, reading real
  ``peak_bytes_in_use`` from HBM. Run this inside a healthy chip window —
  each combo is one fresh process; the per-run soft budget keeps a single
  claim short (see results/perf/tpu_session_r3.md for the claim rules).

    python tools/memory_matrix.py --device cpu --out results/perf/memory_matrix_cpu_r4.jsonl
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, REPO)  # tools.xla_util import when run as a script


def combos(device: str):
    if device == "tpu":
        batch = "64"
        kernels = [
            ("xla", "shared"),  # baseline XLA lowering
            ("pallas", "counter"),  # flash kernel, in-kernel sampling
            ("pallas", "shared"),  # fused kernel, HBM noise stream
        ]
        reps, steps = "5", "4"
    else:
        batch = "8"
        kernels = [("xla", "shared")]
        reps, steps = "3", "2"
    for n in ("150", "512"):
        for remat in ("0", "1"):
            for backend, noise in kernels:
                yield {
                    "max_src_len": n, "remat": remat, "backend": backend,
                    "noise_mode": noise, "batch": batch, "reps": reps,
                    "steps": steps,
                }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--device", choices=("cpu", "tpu"), default="cpu")
    ap.add_argument("--config", default="python")
    ap.add_argument("--out", default="")
    ap.add_argument("--timeout", type=float, default=900.0,
                    help="per-combo hard cap (fresh process each)")
    args = ap.parse_args()
    out_path = args.out or os.path.join(
        REPO, "results", "perf", f"memory_matrix_{args.device}.jsonl")
    os.makedirs(os.path.dirname(out_path), exist_ok=True)

    rows = []
    for combo in combos(args.device):
        cmd = [sys.executable, os.path.join(HERE, "time_memory.py"),
               "--config", args.config,
               "--batch", combo["batch"], "--reps", combo["reps"],
               "--steps", combo["steps"], "--max_src_len", combo["max_src_len"],
               "--remat", combo["remat"], "--backend", combo["backend"],
               "--noise_mode", combo["noise_mode"]]
        env = None
        if args.device == "cpu":
            cmd += ["--platform", "cpu"]
            # CPU combos must not touch the axon PJRT plugin (see
            # tools/xla_util.cpu_child_env for the wedge this avoids)
            from tools.xla_util import cpu_child_env

            env = cpu_child_env()
        t0 = time.monotonic()
        try:
            proc = subprocess.run(cmd, capture_output=True, text=True,
                                  timeout=args.timeout, cwd=REPO, env=env)
        except subprocess.TimeoutExpired:
            rec = {"combo": combo, "error": f"timeout {args.timeout}s"}
            rows.append(rec)
            _append(out_path, rec)
            continue
        if proc.returncode != 0:
            tail = (proc.stderr or "").strip().splitlines()[-2:]
            rec = {"combo": combo, "error": f"rc={proc.returncode}: {' | '.join(tail)}"}
        else:
            try:
                rec = json.loads(proc.stdout.strip().splitlines()[-1])
            except (json.JSONDecodeError, IndexError):
                rec = {"combo": combo, "error": "no JSON in child output"}
        rec["wall_s"] = round(time.monotonic() - t0, 1)
        rows.append(rec)
        _append(out_path, rec)
        print(json.dumps(rec), file=sys.stderr)

    ok = [r for r in rows if "error" not in r]
    print(json.dumps({"device": args.device, "n_ok": len(ok),
                      "n_failed": len(rows) - len(ok), "out": out_path}))


def _append(path: str, rec: dict) -> None:
    with open(path, "a") as f:
        f.write(json.dumps(rec) + "\n")
        f.flush()


if __name__ == "__main__":
    main()

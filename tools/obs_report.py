"""One-screen run report from the telemetry artifacts (ISSUE 7).

Reads any combination of:

* a **metrics JSONL** file (``csat_tpu/obs/metrics.py:MetricsFile`` — the
  serve CLI's ``--metrics_file`` / the train CLI's ``--metrics_file``) and
  renders the last snapshot as an outcome/counter table;
* an **events file** — a flight-recorder dump (post-mortem JSONL,
  ``csat_tpu/obs/events.py``) or a Chrome trace-event JSON export
  (``csat_tpu/obs/trace.py``) — and renders a phase-time table
  (count / total / mean / p95 per span name) plus the lifecycle outcome
  counts found in the event stream;
* the **perf ledger** (``--history``, ``csat_tpu/obs/perfdb.py``) — the
  bench trajectory: one row per run with raw and calibration-normalized
  headline, box fingerprint and degradation flags (ISSUE 10);
* a **request-trace dump** (``--traces``, ``csat_tpu/obs/rtrace.py``) —
  the slowest-N request traces as span trees with per-span durations and
  linked attempt numbers (ISSUE 14).

Usage::

    python tools/obs_report.py --metrics serve_metrics.jsonl \
        --events outputs/postmortem/postmortem_serve_FAILED.jsonl
    python tools/obs_report.py --events outputs/.../host_trace.json
    python tools/obs_report.py --history results/perf/history.jsonl

Runs on the fast-gate artifacts in CI; ``bench.py`` computes its own
phase-time breakdown from the recorder's running totals
(``EventRecorder.totals``) so it needs no artifact round-trip —
``phase_table`` here is the offline equivalent over a dump/trace file,
and ``tools/perf_compare.py`` reuses it for its phase-delta section.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
from typing import Dict, Iterable, List, Optional, Tuple

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from csat_tpu.serve.stats import percentile  # noqa: E402


def load_metrics(path: str) -> List[dict]:
    """All snapshots in a metrics JSONL file, oldest first."""
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out


def load_events(path: str) -> Tuple[dict, List[dict]]:
    """(meta, events) from either a flight-recorder JSONL dump or a Chrome
    trace JSON file — both normalize to dicts with ``name``/``dur``
    (seconds) and optional extra fields."""
    with open(path) as f:
        head = f.read(1).strip()
    if head == "{":
        # could be a one-object trace file OR a JSONL dump whose first line
        # is the {"meta": ...} header — try the whole-file JSON parse first
        try:
            with open(path) as f:
                obj = json.load(f)
            if "traceEvents" in obj:
                events = []
                for ev in obj["traceEvents"]:
                    if ev.get("ph") == "M":
                        continue
                    rec = {"name": ev.get("name"),
                           "ts": ev.get("ts", 0.0) / 1e6}
                    if ev.get("ph") == "X":
                        rec["dur"] = ev.get("dur", 0.0) / 1e6
                    rec.update(ev.get("args") or {})
                    events.append(rec)
                return {"source": "chrome-trace"}, events
        except json.JSONDecodeError:
            pass
    from csat_tpu.obs.events import EventRecorder

    return EventRecorder.load(path)


def phase_table(events: Iterable[dict]) -> Dict[str, Dict[str, float]]:
    """Aggregate span events by name: count, total seconds, mean and p95
    milliseconds. Instant events (no ``dur``) are excluded."""
    durs: Dict[str, List[float]] = {}
    for ev in events:
        d = ev.get("dur")
        if d is None:
            continue
        durs.setdefault(ev["name"], []).append(float(d))
    return {
        name: {
            "count": len(ds),
            "total_s": round(sum(ds), 4),
            "mean_ms": round(sum(ds) / len(ds) * 1e3, 3),
            "p95_ms": round(percentile(ds, 95) * 1e3, 3),
        }
        for name, ds in sorted(durs.items())
    }


def outcome_counts(events: Iterable[dict]) -> Dict[str, int]:
    """Request-lifecycle outcome counts from ``req.*`` instant events."""
    out: Dict[str, int] = {}
    for ev in events:
        name = ev.get("name", "")
        if name.startswith(("req.", "fault.")):
            out[name] = out.get(name, 0) + 1
    return dict(sorted(out.items()))


def _fmt_table(rows: List[Tuple], headers: Tuple) -> str:
    rows = [tuple(str(c) for c in r) for r in rows]
    widths = [max(len(h), *(len(r[i]) for r in rows)) if rows else len(h)
              for i, h in enumerate(headers)]
    def line(cells):
        return "  ".join(c.ljust(w) for c, w in zip(cells, widths)).rstrip()
    sep = "  ".join("-" * w for w in widths)
    return "\n".join([line(headers), sep] + [line(r) for r in rows])


_REPLICA_KEY_RE = re.compile(r"^replica(\d+)_(.+)$")

# (column header, per-replica metric key) for the fleet table; counters
# sum into the fleet totals row, the latency histogram renders as mean ms
_FLEET_COLS = (
    ("submitted", "serve_requests_submitted_total"),
    ("ok", "serve_requests_ok_total"),
    ("failed", "serve_requests_failed_total"),
    ("timeout", "serve_requests_timeout_total"),
    ("shed", "serve_requests_shed_total"),
    ("queue", "serve_queue_depth"),
    ("busy", "serve_slots_occupied"),
    ("gen_tokens", "serve_gen_tokens_total"),
)


# serve_health_state gauge encoding (csat_tpu/serve/fleet.py)
_HEALTH_NAMES = {0: "HEALTHY", 1: "DRAINING", 2: "SICK"}


def split_fleet_snapshot(snap: dict) -> List[dict]:
    """One fleet snapshot (``Fleet.snapshot`` — per-replica series under a
    ``replica<k>_`` key prefix) → per-replica plain dicts, index order.
    Each dict carries its replica index under ``_index`` — elastic fleets
    (ISSUE 13) have gaps: retired indices disappear, replacements land at
    fresh monotonic indices, so list position is not identity."""
    per: Dict[int, dict] = {}
    for key, v in snap.items():
        m = _REPLICA_KEY_RE.match(key)
        if m:
            per.setdefault(int(m.group(1)), {})[m.group(2)] = v
    for k, d in per.items():
        d["_index"] = k
    return [per[k] for k in sorted(per)]


def fleet_table(snaps: List[dict]) -> str:
    """Per-replica counter table (with the health state from each
    replica's ``serve_health_state`` gauge) plus a summed fleet totals
    row, from the replicas' last metrics snapshots."""
    rows: List[Tuple] = []
    totals = {col: 0 for col, _ in _FLEET_COLS}
    for k, snap in enumerate(snaps):
        row: List = [f"replica{snap.get('_index', k)}"]
        health = snap.get("serve_health_state")
        row.append(_HEALTH_NAMES.get(health, "-") if health is not None
                   else "-")
        for col, key in _FLEET_COLS:
            v = snap.get(key, 0) or 0
            row.append(v)
            totals[col] += v
        lat_n = snap.get("serve_request_latency_seconds_count") or 0
        lat_s = snap.get("serve_request_latency_seconds_sum") or 0.0
        row.append(round(lat_s / lat_n * 1e3, 1) if lat_n else "-")
        rows.append(tuple(row))
    rows.append(("fleet", "-", *(totals[c] for c, _ in _FLEET_COLS), "-"))
    return _fmt_table(
        rows,
        ("replica", "health", *(c for c, _ in _FLEET_COLS), "lat_mean_ms"))


# per-tier occupancy gauges (serve/tiering.py, ISSUE 16): shown next to
# the HBM page occupancy whenever any replica reports them
_TIER_COLS = (
    ("host", "serve_tier_host_pages_in_use"),
    ("disk", "serve_tier_disk_pages_in_use"),
)


def kv_pages_table(snaps: List[dict]) -> str:
    """KV page occupancy per replica — HBM in-use / usable (plus peak),
    with the host/disk tier residency columns when any replica runs the
    tiered store, and mesh columns (chip count + worst-chip page load,
    ISSUE 17) when any replica spans more than one chip.  Rectangle-layout
    replicas (0 usable pages) are skipped; returns "" when nothing is
    paged."""
    tiered = any(s.get(key) is not None for s in snaps for _, key in _TIER_COLS)
    meshed = any((s.get("serve_mesh_devices") or 1) > 1 for s in snaps)
    rows: List[Tuple] = []
    for k, s in enumerate(snaps):
        usable = s.get("serve_kv_pages") or 0
        if not usable:
            continue
        used = s.get("serve_kv_pages_in_use") or 0
        row: List = [f"replica{s.get('_index', k)}", used, usable,
                     f"{used / usable:.1%}", s.get("serve_kv_pages_peak") or 0]
        if meshed:
            row += [s.get("serve_mesh_devices") or 1,
                    s.get("serve_kv_pages_in_use_worst_chip")
                    if s.get("serve_kv_pages_in_use_worst_chip") is not None
                    else "-"]
        if tiered:
            row += [s[key] if s.get(key) is not None else "-"
                    for _, key in _TIER_COLS]
        rows.append(tuple(row))
    if not rows:
        return ""
    headers: Tuple = ("replica", "hbm_in_use", "usable", "occ", "peak")
    if meshed:
        headers += ("chips", "worst_chip")
    if tiered:
        headers += tuple(c for c, _ in _TIER_COLS)
    return _fmt_table(rows, headers)


# network front-door gauges/counters (serve/netfront.py, ISSUE 20):
# rendered per replica whenever any snapshot carries the connection gauge
_NET_COLS = (
    ("conns", "serve_net_connections"),
    ("stalled", "serve_net_stalled"),
    ("frames", "serve_net_frames_total"),
    ("stall_drops", "serve_net_stall_drops_total"),
    ("resumes", "serve_net_resumes_total"),
    ("disconnects", "serve_net_disconnects_total"),
    ("malformed", "serve_net_malformed_total"),
)


def net_table(snaps: List[dict]) -> str:
    """Network front-door connection/stall/resume table (ISSUE 20) —
    one row per replica reporting the ``serve_net_*`` series; returns ""
    when no snapshot ran behind a front door."""
    rows: List[Tuple] = []
    for k, s in enumerate(snaps):
        if s.get("serve_net_connections") is None:
            continue
        rows.append((f"replica{s.get('_index', k)}",
                     *(s.get(key, 0) or 0 for _, key in _NET_COLS)))
    if not rows:
        return ""
    return _fmt_table(rows, ("replica", *(c for c, _ in _NET_COLS)))


def trace_lines(path: str, slowest: int = 5) -> List[str]:
    """The slowest-N request traces from a ``Tracer.dump`` JSONL artifact
    (ISSUE 14) as indented span trees — one header row per trace (id,
    status, end-to-end duration, attempts), then its spans in time order
    with per-span durations and extra fields."""
    from csat_tpu.obs.rtrace import load_traces

    traces = load_traces(path)
    done = [t for t in traces if t.get("status")]
    done.sort(key=lambda t: -float(t.get("dur", 0.0)))
    shown = done[:slowest] if slowest else done
    out = [f"== slowest traces ({len(shown)} of {len(traces)} in "
           f"{path}) =="]
    if not shown:
        return out + ["  (no finished traces in dump)"]
    for t in shown:
        out.append(
            f"  {t.get('trace_id', '?')}  status={t.get('status', '?')}  "
            f"dur={float(t.get('dur', 0.0)) * 1e3:.1f}ms  "
            f"attempts={t.get('attempt', 1)}")
        t0 = float(t.get("t0", 0.0))
        rows = []
        for sp in t.get("spans", ()):
            extra = {k: v for k, v in sp.items()
                     if k not in ("name", "t0", "dur", "attempt")}
            rows.append((
                sp.get("name", "?"),
                sp.get("attempt", 1),
                f"+{float(sp.get('t0', t0)) - t0:.4f}s",
                f"{float(sp.get('dur', 0.0)) * 1e3:.2f}",
                " ".join(f"{k}={v}" for k, v in sorted(extra.items())),
            ))
        table = _fmt_table(rows, ("span", "att", "start", "dur_ms", "fields"))
        out.extend("    " + ln for ln in table.splitlines())
    return out


def history_table(history: List[dict]) -> str:
    """The bench trajectory as a table: one row per ledger entry, raw and
    calibration-normalized headline side by side."""
    import time as _time

    rows = []
    for e in history:
        fp = e.get("machine_fingerprint") or {}
        cal = e.get("calibration") or {}
        flags = ",".join(e.get("degraded_reasons") or ()) or "-"
        if e.get("regression", {}).get("kind"):
            flags += f" [regression:{e['regression']['kind']}]"
        rows.append((
            e.get("run_id", "?"),
            _time.strftime("%Y-%m-%d", _time.gmtime(e["ts"]))
            if e.get("ts") else "?",
            f"{fp.get('platform', '?')}×{fp.get('device_count', '?')}"
            if fp else "-",
            e.get("value"),
            e.get("value_cal"),
            "yes" if cal.get("probes") else "no",
            flags,
        ))
    return _fmt_table(rows, ("run", "date", "device", "raw", "cal",
                             "calibrated", "flags"))


def report(metrics_path: Optional[str] = None,
           events_path: Optional[str] = None,
           history_path: Optional[str] = None,
           fleet_paths: Optional[List[str]] = None,
           traces_path: Optional[str] = None,
           slowest: int = 5) -> str:
    """The one-screen report as a string (main() prints it)."""
    sections: List[str] = []
    if fleet_paths:
        # either one fleet metrics file (replica<k>_-prefixed keys, the
        # serve CLI's --replicas N --metrics_file output) or N per-replica
        # metrics files, comma-separated
        snaps: List[dict] = []
        spawned = retired = 0
        lifecycle = False
        for path in fleet_paths:
            all_snaps = load_metrics(path)
            last = all_snaps[-1] if all_snaps else {}
            split = split_fleet_snapshot(last)
            snaps.extend(split if split else [last])
            # fleet-level lifecycle counters ride un-prefixed in the same
            # snapshot as the replica<k>_ series (elastic fleet, ISSUE 13)
            if ("fleet_replicas_spawned_total" in last
                    or "fleet_replicas_retired_total" in last):
                lifecycle = True
                spawned += int(last.get("fleet_replicas_spawned_total", 0))
                retired += int(last.get("fleet_replicas_retired_total", 0))
        section = (f"== fleet ({len(snaps)} replica(s)) ==\n"
                   + fleet_table(snaps))
        if lifecycle:
            section += (f"\nlifecycle: {spawned} spawned, "
                        f"{retired} retired")
        sections.append(section)
        pages = kv_pages_table(snaps)
        if pages:
            sections.append("== kv pages (per tier) ==\n" + pages)
        net = net_table(snaps)
        if net:
            sections.append("== net front door ==\n" + net)
    if metrics_path:
        snaps = load_metrics(metrics_path)
        if snaps:
            last = snaps[-1]
            rows = [(k, v) for k, v in sorted(last.items()) if k != "t"]
            sections.append(
                f"== metrics ({metrics_path}: {len(snaps)} snapshot(s), "
                f"showing last) ==\n" + _fmt_table(rows, ("metric", "value")))
            # latency percentiles when the serving histograms are present
            lat_sum = last.get("serve_request_latency_seconds_sum")
            lat_n = last.get("serve_request_latency_seconds_count")
            if lat_n:
                sections.append(
                    f"mean OK latency: {lat_sum / lat_n * 1e3:.1f} ms "
                    f"over {lat_n} request(s)")
            pages = kv_pages_table([last])
            if pages:
                sections.append("== kv pages (per tier) ==\n" + pages)
            net = net_table([last])
            if net:
                sections.append("== net front door ==\n" + net)
    if events_path:
        meta, events = load_events(events_path)
        title = meta.get("component") or meta.get("source") or "events"
        if meta.get("reason"):
            title += f" (reason: {meta['reason']})"
        phases = phase_table(events)
        if phases:
            rows = [(n, p["count"], p["total_s"], p["mean_ms"], p["p95_ms"])
                    for n, p in phases.items()]
            sections.append(
                f"== phase time — {title} ({events_path}) ==\n" + _fmt_table(
                    rows, ("phase", "count", "total_s", "mean_ms", "p95_ms")))
        outcomes = outcome_counts(events)
        if outcomes:
            sections.append("== outcomes/faults ==\n" + _fmt_table(
                list(outcomes.items()), ("event", "count")))
        if not phases and not outcomes:
            sections.append(f"(no span or lifecycle events in {events_path})")
    if traces_path:
        sections.append("\n".join(trace_lines(traces_path, slowest)))
    if history_path:
        from csat_tpu.obs import perfdb

        history = perfdb.load_history(history_path)
        if history:
            sections.append(
                f"== bench trajectory ({history_path}: {len(history)} "
                f"run(s)) ==\n" + history_table(history))
        else:
            sections.append(f"(no ledger entries in {history_path})")
    if not sections:
        sections.append(
            "nothing to report: pass --metrics, --events, --history, "
            "--traces and/or --fleet")
    return "\n\n".join(sections)


def main(argv: Optional[List[str]] = None) -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--metrics", default="",
                   help="metrics JSONL file (MetricsFile format)")
    p.add_argument("--events", default="",
                   help="flight-recorder dump (JSONL) or Chrome trace JSON")
    p.add_argument("--history", default="",
                   help="perf ledger JSONL (results/perf/history.jsonl)")
    p.add_argument("--fleet", default="",
                   help="fleet metrics: ONE fleet snapshot file "
                        "(replica<k>_-prefixed keys, `csat_tpu serve "
                        "--replicas N --metrics_file ...`) or comma-"
                        "separated per-replica metrics JSONL files")
    p.add_argument("--traces", default="",
                   help="request-trace dump JSONL (Tracer.dump / the "
                        "serve CLI's --traces_file)")
    p.add_argument("--slowest", type=int, default=5,
                   help="how many of the slowest traces to render")
    args = p.parse_args(argv)
    fleet = [s for s in args.fleet.split(",") if s] if args.fleet else None
    print(report(args.metrics or None, args.events or None,
                 args.history or None, fleet,
                 args.traces or None, args.slowest))


if __name__ == "__main__":
    main()

"""One-shot padding-tax report for a corpus.

Prints, as one JSON document, the length-bucket histogram and the
padded-vs-real node accounting (`csat_tpu.data.bucketing.bucket_histogram`)
for a processed split: how many of the nodes the fixed-shape pipeline
feeds are real vs PAD, what the configured bucket plan would feed
instead, and the projected shrink of the O(N²) relation-matrix bytes —
the numbers that justify (or size) a ``bucketing=True`` config before
committing to its compile set.

Usage::

    python tools/padding_stats.py --config python --split train
    python tools/padding_stats.py --config python --src-lens 37,75,150
    python tools/padding_stats.py --synthetic 256   # no corpus needed

``--synthetic N`` generates the test-suite's synthetic corpus (N train
samples) into a temp dir, so the tool runs anywhere.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__.split("\n\n")[0])
    ap.add_argument("--config", default="python")
    ap.add_argument("--split", default="train")
    ap.add_argument("--data-dir", default="", help="override cfg.data_dir")
    ap.add_argument("--max-src-len", type=int, default=0)
    ap.add_argument("--src-lens", default="",
                    help="comma list overriding bucket_src_lens")
    ap.add_argument("--tgt-lens", default="",
                    help="comma list overriding bucket_tgt_lens")
    ap.add_argument("--budget", type=int, default=0,
                    help="bucket_token_budget override")
    ap.add_argument("--synthetic", type=int, default=0,
                    help="generate an N-sample synthetic corpus instead of "
                         "reading cfg.data_dir")
    args = ap.parse_args()

    from csat_tpu.configs import get_config
    from csat_tpu.data.bucketing import bucket_histogram
    from csat_tpu.data.dataset import ASTDataset
    from csat_tpu.data.vocab import load_vocab

    overrides: dict = {"bucketing": True}
    if args.max_src_len:
        overrides["max_src_len"] = args.max_src_len
    if args.src_lens:
        overrides["bucket_src_lens"] = tuple(
            int(v) for v in args.src_lens.split(","))
    if args.tgt_lens:
        overrides["bucket_tgt_lens"] = tuple(
            int(v) for v in args.tgt_lens.split(","))
    if args.budget:
        overrides["bucket_token_budget"] = args.budget

    if args.synthetic:
        from csat_tpu.data.synthetic import make_corpus

        data_dir = tempfile.mkdtemp(prefix="padding_stats_")
        make_corpus(data_dir, n_train=args.synthetic,
                    n_dev=max(args.synthetic // 4, 1),
                    n_test=max(args.synthetic // 4, 1), seed=0)
        overrides["data_dir"] = data_dir
    elif args.data_dir:
        overrides["data_dir"] = args.data_dir

    cfg = get_config(args.config, **overrides)
    src_vocab, tgt_vocab = load_vocab(cfg.data_dir)
    ds = ASTDataset(cfg, args.split, src_vocab, tgt_vocab)
    report = bucket_histogram(cfg, ds.arrays)
    report["config"] = args.config
    report["split"] = args.split
    report["data_dir"] = cfg.data_dir
    print(json.dumps(report, indent=2))


if __name__ == "__main__":
    main()

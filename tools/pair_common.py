"""Shared plumbing for the paired JAX-vs-torch-reference experiments.

Single source of truth for the three things the pairing tools kept
restating independently (r5 review): the reference-import stubs, the
CPU-budget width→dims rule, and the reference-model constructor call.
"""

from __future__ import annotations

import importlib.util
import os
import sys
import types

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)
REF = "/root/reference"

__all__ = ["import_reference", "cpu_dims", "build_reference_model"]


def import_reference():
    """Import the reference model package with the dependency stubs the
    parity tests use (torch_geometric / ipdb / old-torch typing shims).
    → (module, utils, optimizer-module)."""
    import typing

    import torch.utils.data.dataset as tud

    if "torch_geometric" not in sys.modules:
        tg = types.ModuleType("torch_geometric")
        tgd = types.ModuleType("torch_geometric.data")

        class Data:
            def __init__(self, **kw):
                self.__dict__.update(kw)

        tgd.Data = Data
        tg.data = tgd
        sys.modules["torch_geometric"] = tg
        sys.modules["torch_geometric.data"] = tgd
    sys.modules.setdefault("ipdb", types.ModuleType("ipdb"))
    if not hasattr(tud, "T_co"):
        tud.T_co = typing.TypeVar("T_co", covariant=True)
    if REF not in sys.path:
        sys.path.insert(0, REF)
    import module as ref_module
    import utils as ref_utils

    # script/__init__ pulls in ignite; load the optimizer file directly
    spec = importlib.util.spec_from_file_location(
        "ref_optimizer", os.path.join(REF, "script", "optimizer.py"))
    ref_optimizer = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(ref_optimizer)
    return ref_module, ref_utils, ref_optimizer


def cpu_dims(width: int = 128, sequential: bool = False) -> dict:
    """The CPU-budget pairing dims at ``width`` (the rule every pairing
    tool must share): sbm_enc/hidden/pegen = w, pe = w//2, ff = 4w,
    2+2 layers, clusters (8,8), max_tgt_len 30. ``sequential`` drops the
    pegen-stack dims (seq-PE configs set pe_dim=0; sizing them would
    violate ``Config.validate``)."""
    w = width
    dims = dict(
        pe_dim=w // 2,
        pegen_dim=w,
        sbm_enc_dim=w,
        hidden_size=w,
        num_heads=4,
        num_layers=2,
        sbm_layers=2,
        clusters=(8, 8),
        dim_feed_forward=4 * w,
        max_tgt_len=30,
    )
    if sequential:
        dims.pop("pe_dim")
        dims.pop("pegen_dim")
    return dims


def build_reference_model(ref_module, cfg, src_vocab_size: int,
                          tgt_vocab_size: int):
    """Construct the reference ``CSATrans`` from a csat-tpu ``Config`` —
    the ONE ctor call both the torch baseline trainer and the init porter
    use, so seed-for-seed init pairing cannot drift between call sites.
    Seeds torch with ``cfg.seed`` immediately before construction."""
    import torch

    torch.manual_seed(cfg.seed)
    return ref_module.csa_trans.CSATrans(
        src_vocab_size=src_vocab_size, tgt_vocab_size=tgt_vocab_size,
        hidden_size=cfg.hidden_size, num_heads=cfg.num_heads,
        num_layers=cfg.num_layers, sbm_layers=cfg.sbm_layers,
        use_pegen=cfg.use_pegen, dim_feed_forward=cfg.dim_feed_forward,
        dropout=cfg.dropout, pe_dim=cfg.pe_dim, pegen_dim=cfg.pegen_dim,
        sbm_enc_dim=cfg.sbm_enc_dim, clusters=list(cfg.clusters),
        full_att=cfg.full_att, max_src_len=cfg.max_src_len,
    )

"""Diff two bench runs from the perf ledger and attribute the delta (ISSUE 10).

Reads ``results/perf/history.jsonl`` (``csat_tpu/obs/perfdb.py`` — every
``bench.py`` run appends its full record, calibration block and machine
fingerprint) and renders a one-screen comparison in the same table style as
``tools/obs_report.py``:

* run header — id, date, host/device fingerprint, matmul-probe GFLOP/s;
* headline — raw and calibration-normalized values plus the
  ``{environment, code, unexplained}`` attribution of the delta (the
  automated version of the interleaved A/B the r05→r08 episode needed by
  hand); legacy entries imported with ``calibration: null`` attribute to
  ``unexplained`` — unattributable, said out loud;
* per-variant step-time deltas;
* phase-time deltas (``phase_time{}`` from the records, aggregated through
  ``tools/obs_report.py:phase_table``).

Usage::

    python tools/perf_compare.py                 # ledger best vs newest run
    python tools/perf_compare.py --a run_X --b run_Y
    python tools/perf_compare.py --a -2 --b -1   # by ledger index
    python tools/perf_compare.py --import-legacy # backfill BENCH_r01..r05
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import time
from typing import List, Optional, Tuple

HERE = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, HERE)

from csat_tpu.obs import perfdb  # noqa: E402
from tools.obs_report import _fmt_table, phase_table  # noqa: E402


def default_history_path() -> str:
    p = os.environ.get("BENCH_HISTORY_FILE")
    if p is None:
        try:
            from csat_tpu.configs import get_config

            p = get_config("python").bench_history_file
        except Exception:  # noqa: BLE001
            p = "results/perf/history.jsonl"
    return p if (not p or os.path.isabs(p)) else os.path.join(HERE, p)


# --------------------------------------------------------------------------
# legacy backfill
# --------------------------------------------------------------------------

def import_legacy(history_path: str, pattern: str = "BENCH_r0*.json") -> List[str]:
    """One-shot backfill: fold the archival ``BENCH_r01..r05.json`` driver
    captures into the ledger with ``calibration: null`` so the trajectory
    table is not empty on day one.  Idempotent — run_ids already present
    are skipped.  Returns the run_ids appended."""
    have = {e.get("run_id") for e in perfdb.load_history(history_path)}
    appended: List[str] = []
    for path in sorted(glob.glob(os.path.join(HERE, pattern))):
        run_id = os.path.splitext(os.path.basename(path))[0].split("_")[-1].lower()
        if run_id in have:
            continue
        try:
            with open(path) as f:
                raw = json.load(f)
        except (OSError, ValueError):
            continue
        parsed = raw.get("parsed") or {}
        bench_out = dict(parsed)
        bench_out.setdefault("metric", perfdb.HEADLINE_METRIC)
        bench_out.setdefault("value", 0.0)
        bench_out["calibration"] = None
        bench_out["machine_fingerprint"] = None
        # no calibration: raw == normalized, by definition of the ratio
        bench_out["nodes_per_sec_per_chip_cal"] = bench_out["value"]
        reasons = []
        if not parsed:
            reasons.append("no_results")
            bench_out["notes"] = (
                f"legacy import: driver capture rc={raw.get('rc')} had no "
                f"parseable bench line")
        elif parsed.get("degraded"):
            reasons.append("no_device")
        bench_out["degraded_reasons"] = reasons
        entry = perfdb.make_entry(
            bench_out, run_id=run_id, ts=os.path.getmtime(path),
            source=os.path.basename(path))
        perfdb.append_entry(history_path, entry)
        appended.append(run_id)
    return appended


# --------------------------------------------------------------------------
# comparison rendering
# --------------------------------------------------------------------------

def _resolve(history: List[dict], sel: Optional[str],
             fallback: Optional[dict]) -> Optional[dict]:
    """A ledger entry by run_id, by (possibly negative) index, or the
    fallback when no selector was given."""
    if sel is None or sel == "":
        return fallback
    for e in history:
        if e.get("run_id") == sel:
            return e
    try:
        return history[int(sel)]
    except (ValueError, IndexError):
        raise SystemExit(
            f"no ledger entry {sel!r} (have "
            f"{[e.get('run_id') for e in history]})")


def _when(e: dict) -> str:
    ts = e.get("ts")
    if not ts:
        return "?"
    return time.strftime("%Y-%m-%d %H:%M", time.gmtime(ts))


def _fp_line(e: dict) -> Tuple[str, str, str]:
    fp = e.get("machine_fingerprint") or {}
    cal = e.get("calibration") or {}
    probes = cal.get("probes") or {}
    mm = probes.get("matmul_f32_gflops")
    return (
        f"{fp.get('host', '?')}/{fp.get('platform', '?')}"
        f"×{fp.get('device_count', '?')}",
        fp.get("id", "-"),
        f"{mm:.1f}" if isinstance(mm, (int, float)) else "-",
    )


def _variants(e: dict) -> List[dict]:
    return (e.get("record") or {}).get("all_variants") or []


def _vkey(v: dict) -> str:
    return f"{v.get('backend')}:{v.get('dtype')}:{v.get('mode', 'fixed')}"


def _phase_events(e: dict) -> List[dict]:
    """Pseudo span events from each variant's ``phase_time{}`` block, so
    :func:`tools.obs_report.phase_table` can aggregate them exactly like a
    flight-recorder dump."""
    events = []
    for v in _variants(e):
        for name, dur in (v.get("phase_time") or {}).items():
            events.append({"name": f"{_vkey(v)}/{name}", "dur": float(dur)})
    return events


def _pct(new: float, old: float) -> str:
    if not old:
        return "-"
    return f"{(new / old - 1.0) * 100.0:+.1f}%"


def compare(a: dict, b: dict) -> str:
    """The one-screen comparison report (``a`` = baseline, ``b`` = candidate)."""
    sections: List[str] = []
    rows = []
    for tag, e in (("a (base)", a), ("b (new)", b)):
        box, fpid, mm = _fp_line(e)
        rows.append((tag, e.get("run_id"), _when(e), box, fpid, mm,
                     e.get("value"), e.get("value_cal"),
                     ",".join(e.get("degraded_reasons") or ()) or "-"))
    sections.append("== runs ==\n" + _fmt_table(
        rows, ("", "run", "when (utc)", "box", "fp", "matmul_gflops",
               "raw", "cal", "degraded")))

    att = perfdb.attribute_delta(a, b)
    if not att.get("comparable"):
        sections.append(f"headline not comparable: {att.get('why')}")
    else:
        rows = [
            ("total", f"{att['total_pct']:+.2f}%",
             "raw headline delta (b vs a)"),
            ("environment", f"{att['environment_pct']:+.2f}%",
             "machine-speed delta per the calibration probes"),
            ("code", f"{att['code_pct']:+.2f}%",
             "residual beyond the noise tolerance "
             f"(±{att['noise_tol_pct']}%)"),
            ("unexplained", f"{att['unexplained_pct']:+.2f}%",
             "residual within noise"
             if att["calibrated"] else
             "whole residual — a side lacks calibration"),
        ]
        sections.append(
            f"== headline attribution — verdict: {att['verdict']} ==\n"
            + _fmt_table(rows, ("component", "delta", "meaning")))

    va = {_vkey(v): v for v in _variants(a)}
    vb = {_vkey(v): v for v in _variants(b)}
    common = [k for k in va if k in vb]
    if common:
        rows = []
        for k in common:
            sa, sb = va[k].get("step_ms"), vb[k].get("step_ms")
            rows.append((k, sa, sb,
                         _pct(sb, sa) if sa and sb else "-"))
        sections.append("== per-variant step time (ms) ==\n" + _fmt_table(
            rows, ("variant", "a", "b", "delta")))

    pa, pb = phase_table(_phase_events(a)), phase_table(_phase_events(b))
    shared = [n for n in pa if n in pb]
    if shared:
        rows = [(n, pa[n]["total_s"], pb[n]["total_s"],
                 _pct(pb[n]["total_s"], pa[n]["total_s"]))
                for n in shared]
        sections.append("== phase time (s) ==\n" + _fmt_table(
            rows, ("phase", "a", "b", "delta")))
    return "\n\n".join(sections)


def main(argv: Optional[List[str]] = None) -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--history", default="",
                   help="ledger path (default: the bench_history_file knob)")
    p.add_argument("--a", default="",
                   help="baseline entry: run_id or index (default: ledger best)")
    p.add_argument("--b", default="",
                   help="candidate entry: run_id or index (default: newest)")
    p.add_argument("--import-legacy", action="store_true",
                   help="backfill BENCH_r01..r05.json into the ledger "
                        "(calibration: null), then exit")
    args = p.parse_args(argv)
    path = args.history or default_history_path()
    if args.import_legacy:
        added = import_legacy(path)
        print(f"imported {len(added)} legacy record(s) into {path}: "
              f"{', '.join(added) or '(none — already present)'}")
        return
    history = perfdb.load_history(path)
    if not history:
        raise SystemExit(
            f"empty ledger {path} — run bench.py (or --import-legacy) first")
    b = _resolve(history, args.b, perfdb.last_entry(history))
    best = perfdb.best_entry(history)
    a = _resolve(history, args.a,
                 best if (best is not None and best is not b)
                 else (history[-2] if len(history) > 1 else history[0]))
    if a is None or b is None:
        raise SystemExit("could not resolve two entries to compare")
    print(compare(a, b))


if __name__ == "__main__":
    main()

#!/bin/bash
# TPU-relay watch loop: claim-free TCP tick every ~2 min; only when the
# relay process is up does it spend one real backend-init probe
# (bench.py --probe, self-limiting) to confirm the chip answers. Appends
# one line per tick to the log. The moment a full probe succeeds it
# LAUNCHES tools/tpu_recovery.sh itself (windows have lasted minutes —
# waiting for an operator forfeits them) and exits.
#
# Usage: bash tools/probe_loop.sh [logfile] [interval_s] [--no-launch]
set -u
cd "$(dirname "$0")/.."
LOG=${1:-results/perf/probe_r4.log}
INTERVAL=${2:-120}
LAUNCH=1
[ "${3:-}" = "--no-launch" ] && LAUNCH=0

while true; do
  TS=$(date -u +%Y-%m-%dT%H:%M:%SZ)
  if python tools/relay_probe.py --quiet; then
    PROBE_OUT=$(mktemp)
    timeout 150 python bench.py --probe > "$PROBE_OUT" 2>&1
    RC=$?
    echo "$TS relay=up probe_rc=$RC $(tail -1 "$PROBE_OUT")" >> "$LOG"
    rm -f "$PROBE_OUT"
    if [ "$RC" -eq 0 ]; then
      echo "$TS ALIVE" >> "$LOG"
      if [ "$LAUNCH" -eq 1 ]; then
        echo "$TS launching tpu_recovery.sh" >> "$LOG"
        bash tools/tpu_recovery.sh results/perf >> "$LOG" 2>&1
        echo "$(date -u +%Y-%m-%dT%H:%M:%SZ) tpu_recovery.sh rc=$?" >> "$LOG"
      fi
      exit 0
    fi
  else
    echo "$TS relay=down" >> "$LOG"
  fi
  sleep "$INTERVAL"
done

#!/bin/bash
# TPU-relay watch loop: claim-free TCP tick every ~2 min; only when the
# relay process is up does it spend one real backend-init probe
# (bench.py --probe, self-limiting) to confirm the chip answers. Appends
# one line per tick to the log; exits the moment a full probe succeeds so
# an orchestrator (or the operator) can launch tools/tpu_recovery.sh into
# the fresh window.
#
# Usage: bash tools/probe_loop.sh [logfile] [interval_s]
set -u
cd "$(dirname "$0")/.."
LOG=${1:-results/perf/probe_r4.log}
INTERVAL=${2:-120}

while true; do
  TS=$(date -u +%Y-%m-%dT%H:%M:%SZ)
  if python tools/relay_probe.py --quiet; then
    PROBE_OUT=$(mktemp)
    timeout 150 python bench.py --probe > "$PROBE_OUT" 2>&1
    RC=$?
    echo "$TS relay=up probe_rc=$RC $(tail -1 "$PROBE_OUT")" >> "$LOG"
    rm -f "$PROBE_OUT"
    if [ "$RC" -eq 0 ]; then
      echo "$TS ALIVE" >> "$LOG"
      exit 0
    fi
  else
    echo "$TS relay=down" >> "$LOG"
  fi
  sleep "$INTERVAL"
done

#!/bin/bash
# Round-5 sequential job chain for the single CPU core: wait for the
# frozen-PAD 24-epoch A/B (pid $1), then run the bf16 12-epoch rerun
# (checkpoints kept, pairs with the existing f32 pad_row=zero rows), then
# the CPU memory matrix with the new XLA static-memory analysis.
set -u
cd "$(dirname "$0")/.."
AB_PID=${1:?pid of the frozen A/B run}
LOG=results/r5_chain.log
say() { echo "[$(date -u +%T)] $*" >> "$LOG"; }

# single-instance lock: a double launch would run the identical bf16 rerun
# twice into the same output dir, interleaving checkpoint writes
LOCK=/tmp/r5_chain.pid
if [ -f "$LOCK" ] && kill -0 "$(cat "$LOCK")" 2>/dev/null; then
  say "another chain instance ($(cat "$LOCK")) is live — exiting"
  exit 1
fi
echo $$ > "$LOCK"
trap 'rm -f "$LOCK"' EXIT

say "chain armed behind pid $AB_PID"
while kill -0 "$AB_PID" 2>/dev/null; do sleep 60; done
say "A/B finished; launching bf16 12-epoch rerun"

# every child is CPU-only: scrub the axon plugin env so a half-dead relay
# cannot hang interpreter startup (tools/xla_util.cpu_child_env rationale)
CPUENV="env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu"

# 4 heads + seed 2021 reproduces r4's sbm_bf16 run (3.47 test BLEU)
# deterministically — this time KEEPING its epoch checkpoints, so the same
# weights can be decoded under both dtypes (train-vs-decode attribution)
$CPUENV python tools/train_real.py --data_dir ./data/stdlib_python \
  --variant sbm --epochs 12 --compute_dtype bfloat16 --tag bf16r5 \
  --out ./outputs/r5bf16 >> results/real_stdlib/train_bf16_r5.log 2>&1
say "bf16 rerun rc=$?; launching f32-decode rescore of its checkpoints"

$CPUENV python tools/reeval_ckpt.py \
  --run_dir outputs/r5bf16/final_exp/real_stdlib_sbm_bf16r5 \
  --split test --compute_dtype float32 \
  >> results/real_stdlib/train_bf16_r5.log 2>&1
say "f32 rescore rc=$?; launching bf16-decode rescore (same ckpts, control)"

$CPUENV python tools/reeval_ckpt.py \
  --run_dir outputs/r5bf16/final_exp/real_stdlib_sbm_bf16r5 \
  --split test \
  >> results/real_stdlib/train_bf16_r5.log 2>&1
say "bf16 rescore rc=$?; launching CPU memory matrix"

$CPUENV python tools/memory_matrix.py --device cpu \
  --out results/perf/memory_matrix_cpu_r5.jsonl >> "$LOG" 2>&1
say "memory matrix rc=$?; chain done"

"""Re-score saved epoch checkpoints with the UNIFIED eval metric.

VERDICT r4 weak #4: the paired tables juxtaposed two different dev
metrics — the JAX runs logged mean per-sentence smoothed BLEU on 0–1
(the reference BLEU4 validation metric) while the torch runs logged
corpus BLEU ×100 from ``eval_accuracies``. This tool loads a run's orbax
epoch checkpoints and re-decodes the requested split through the SAME
``eval_accuracies`` pipeline used for test scoring, producing directly
comparable corpus-BLEU(×100) curves for both frameworks.

    python tools/reeval_ckpt.py \
        --run_dir outputs/r4e24/final_exp/real_stdlib_sbm_h8e24 \
        --split dev --epochs 16 20 24
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--run_dir", required=True,
                   help="run output dir containing summary.json + checkpoints/")
    p.add_argument("--split", default="dev", choices=["dev", "test"])
    p.add_argument("--epochs", type=int, nargs="*", default=[],
                   help="checkpoint epochs to score (default: all on disk)")
    p.add_argument("--out", default="", help="default: <run_dir>/reeval_<split>.json")
    p.add_argument("--compute_dtype", default="",
                   choices=["", "float32", "bfloat16"],
                   help="decode-time activation dtype override — decouples "
                        "training dtype from eval dtype (params are always "
                        "f32), for the bf16 train-vs-decode attribution")
    p.add_argument("--eval_graph", default="", choices=["", "sample", "expected"],
                   help="SBM graph mode at decode (configs.Config."
                        "eval_graph; 'expected' = deterministic eval)")
    p.add_argument("--eval_seeds", type=int, nargs="*", default=[],
                   help="decode-RNG seeds to sweep (default: the trainer's "
                        "cfg.seed+777). The SBM samples its graph during "
                        "eval too, so test/dev BLEU is a random variable in "
                        "the decode key — sweeping seeds measures that "
                        "variance (discovered r5: ±0.3+ BLEU on the 200-"
                        "sample test split)")
    args = p.parse_args()

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", "cpu")

    from csat_tpu.utils.cache import enable_compilation_cache

    enable_compilation_cache()

    with open(os.path.join(args.run_dir, "summary.json")) as f:
        summary = json.load(f)
    run_args = summary["config"]

    from csat_tpu.configs import get_config
    from csat_tpu.data.dataset import ASTDataset, iterate_batches
    from csat_tpu.train import Trainer
    from csat_tpu.train.checkpoint import latest_step, restore_latest
    from csat_tpu.train.loop import _decode_dataset
    from csat_tpu.metrics import bleu_output_transform, eval_accuracies

    if "resolved_config" in summary:
        # new-style summaries carry the fully-resolved Config — no sentinel
        # re-derivation at all (tools/train_real.py stamps it)
        from csat_tpu.configs import config_from_dict

        cfg = config_from_dict(summary["resolved_config"])
        overrides = {}
        if args.compute_dtype:
            overrides["compute_dtype"] = args.compute_dtype
        if args.eval_graph:
            overrides["eval_graph"] = args.eval_graph
        if overrides:
            cfg = cfg.replace(**overrides)
            cfg.validate()
    else:
        # legacy summaries: rebuild the cfg as tools/train_real.py did.
        # Unset sentinels are gated explicitly per field (ADVICE r5: bare
        # truthiness silently dropped numeric-0.0 overrides): floor's
        # sentinel is ""/None — a numeric 0.0 (the quirk-fix clamp) is a
        # real value; num_heads/width can never legitimately be 0, and a
        # 0 seed from the legacy argparse default meant "use the config
        # default", matching what training actually ran with.
        def _set(key, *, unset=(None, "")):
            return run_args.get(key) not in unset

        from tools.pair_common import cpu_dims

        name = run_args.get("config") or (
            "python_full_att" if run_args["variant"] == "full_att" else "python")
        sequential = False
        if run_args.get("config"):
            sequential = get_config(run_args["config"]).pe_dim == 0
        width = run_args.get("width")
        dims = {} if run_args.get("full_dims") else cpu_dims(
            width if width not in (None, 0) else 128, sequential=sequential)
        if _set("backend"):
            dims["backend"] = run_args["backend"]
        if _set("num_heads", unset=(None, 0)):
            dims["num_heads"] = run_args["num_heads"]
        if _set("compute_dtype"):
            dims["compute_dtype"] = run_args["compute_dtype"]
        if args.compute_dtype:
            dims["compute_dtype"] = args.compute_dtype
        if args.eval_graph:
            dims["eval_graph"] = args.eval_graph
        if _set("floor"):
            dims["sbm_floor"] = float(run_args["floor"])
        if _set("seed", unset=(None, 0)):
            dims["seed"] = run_args["seed"]
        if _set("pad_row"):
            dims["pad_row"] = run_args["pad_row"]
        cfg = get_config(
            name, data_dir=run_args["data_dir"],
            batch_size=run_args["batch_size"], **dims,
        )

    trainer = Trainer(cfg, log=lambda m: None)
    ds = ASTDataset(cfg, args.split, trainer.src_vocab, trainer.tgt_vocab)
    example = next(iterate_batches(ds, cfg.batch_size, shuffle=False))
    state = trainer.init_state(example)

    ck_dir = os.path.join(args.run_dir, "checkpoints")
    epochs = args.epochs or sorted(
        int(d) for d in os.listdir(ck_dir) if d.isdigit())
    assert epochs, f"no checkpoints under {ck_dir}"

    eval_seeds = args.eval_seeds or [cfg.seed + 777]
    results = []
    for ep in epochs:
        st, _ = restore_latest(ck_dir, state, ep)
        for es in eval_seeds:
            t0 = time.monotonic()
            hyps, refs = [], []
            for y_pred, target in _decode_dataset(
                trainer.model, st.params, ds, cfg, jax.random.key(es),
                trainer.decode_fn, host_shard=False,
            ):
                h, r = bleu_output_transform(y_pred, target, trainer.tgt_vocab.i2w)
                hyps.extend(h)
                refs.extend(r)
            hypotheses = {i: [" ".join(x)] for i, x in enumerate(hyps)}
            references = {i: [" ".join(x)] for i, x in enumerate(refs)}
            bleu, rouge_l, meteor, _, _ = eval_accuracies(hypotheses, references)
            rec = {"epoch": ep, "split": args.split, "eval_seed": es,
                   "bleu": round(bleu, 4), "rouge_l": round(rouge_l, 4),
                   "meteor": round(meteor, 4),
                   "wall_s": round(time.monotonic() - t0, 1)}
            results.append(rec)
            print(json.dumps(rec), flush=True)

    suffix = f"_{args.compute_dtype}" if args.compute_dtype else ""
    if args.eval_graph:
        suffix += f"_{args.eval_graph}"
    if args.eval_seeds:
        suffix += "_seeds"
    out = args.out or os.path.join(
        args.run_dir, f"reeval_{args.split}{suffix}.json")
    with open(out, "w") as f:
        json.dump({"run_dir": args.run_dir, "metric": "corpus_bleu_x100",
                   "eval_compute_dtype": cfg.compute_dtype,
                   "train_compute_dtype": run_args.get("compute_dtype") or
                   "float32",
                   "eval_graph": cfg.eval_graph,
                   "results": results}, f, indent=1)


if __name__ == "__main__":
    main()

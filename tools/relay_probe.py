"""Claim-free TPU-relay liveness probe.

The axon chip grant is claim-based and fragile: a full ``jax.devices()``
probe claims the chip, and claim churn against a wedged or busy relay is
exactly what poisons it (results/perf/tpu_session_r3.md). But the relay
itself (/root/.relay.py, driver infrastructure) is a plain TCP fan-in on
localhost ports — a bare ``connect()`` is answered (with a 0-byte open
marker pumped to the far side) or refused instantly, holds no chip claim,
and cannot wedge anything.

Protocol observed 2026-07-31: relay listens on 127.0.0.1:{8082,8083,...};
when its stdio far end (the driver tunnel) is gone the process dies and
connects are refused. TCP-accept therefore means "relay process up", which
is necessary-but-not-sufficient for a usable chip — callers that get
``alive`` may follow up with one real ``bench.py --probe`` (which performs
an actual backend init) before spending a claim on measurement work.

Exit codes: 0 = a relay port accepted, 3 = all refused/timed out.

    python tools/relay_probe.py [--quiet]
"""

from __future__ import annotations

import json
import socket
import sys

# first ports of each triple in /root/.relay.py's PORTS list; one accept
# anywhere is enough
PORTS = (8082, 8083, 8087, 8092, 8102, 8112)


def relay_alive(timeout_s: float = 2.0) -> int | None:
    """Return the first accepting relay port, or None."""
    for port in PORTS:
        try:
            with socket.create_connection(("127.0.0.1", port), timeout=timeout_s):
                return port
        except OSError:
            continue
    return None


def main() -> None:
    port = relay_alive()
    if "--quiet" not in sys.argv:
        print(json.dumps({"relay_alive": port is not None, "port": port}))
    sys.exit(0 if port is not None else 3)


if __name__ == "__main__":
    main()

"""Ring attention at the registered long-AST size (N=512): parity + timing.

The ``python_long``/``java_long`` configs register ``max_src_len=512,
noise_mode="counter", seq_impl="ring", remat=True`` — but until round 4
nothing had ever executed the ring path at that size (VERDICT r3 weak #3;
every ring test ran N≤64/128). This tool runs the exact registered
combination end-to-end at tiny model dims and records:

1. kernel-level parity: ``ring_sbm_attention`` on a data×seq mesh at N=512
   vs the single-device materialized-noise mirror (bit-identical ΣA,
   fp32-tolerance outputs);
2. end-to-end train-step parity: dp2×sp4 ``seq_impl="ring"`` vs
   ``seq_impl="allgather"`` loss on the same batch — ring must be a pure
   communication choice;
3. wall times (compile + steady-state step) for the artifact.

On CPU this runs under the 8-virtual-device platform; on a real multichip
TPU the same code paths ride ICI. Writes one JSON to --out.

    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python tools/ring512_check.py --out results/perf/ring512_cpu_r4.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="")
    ap.add_argument("--n", type=int, default=512)
    ap.add_argument("--steps", type=int, default=3)
    args = ap.parse_args()

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()

    import jax

    # the axon plugin ignores the env var; only the config update reliably
    # keeps this CPU-mesh check off the (possibly wedged) TPU relay
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from csat_tpu.parallel import build_mesh
    from csat_tpu.parallel.ring import ring_sbm_attention
    from csat_tpu.utils.compat import use_mesh

    report: dict = {"n": args.n, "device": jax.devices()[0].platform,
                    "n_devices": jax.device_count()}

    # ---- 1. kernel-level ring@N parity vs materialized-noise mirror ------
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))
    from tests.test_flash_ops import SEED, _inputs, _xla_mirror

    b, h, dh, kk = 1, 2, 16, 4
    qargs = _inputs(b=b, h=h, n=args.n, dh=dh, kk=kk)
    t0 = time.perf_counter()
    out_x, gs_x = _xla_mirror(*qargs, SEED)
    jax.block_until_ready(out_x)
    mirror_s = time.perf_counter() - t0

    mesh = build_mesh((("data", 1), ("seq", 4)))
    qs = NamedSharding(mesh, P("data", None, "seq", None))
    with use_mesh(mesh):
        sharded = (
            *(jax.device_put(t, qs) for t in qargs[:5]),
            jax.device_put(qargs[5], NamedSharding(mesh, P())),
            jax.device_put(qargs[6], NamedSharding(mesh, P("data", "seq"))),
        )
        ring_fn = jax.jit(lambda *a: ring_sbm_attention(*a, SEED))
        t0 = time.perf_counter()
        out_r, gs_r = jax.block_until_ready(ring_fn(*sharded))
        compile_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        for _ in range(args.steps):
            out_r, gs_r = ring_fn(*sharded)
        jax.block_until_ready(out_r)
        step_s = (time.perf_counter() - t0) / args.steps

    graph_identical = bool(np.array_equal(np.asarray(gs_r), np.asarray(gs_x)))
    max_abs = float(np.max(np.abs(np.asarray(out_r) - np.asarray(out_x))))
    report["kernel"] = {
        "graph_sums_bit_identical": graph_identical,
        "out_max_abs_diff": max_abs,
        "ring_compile_s": round(compile_s, 1),
        "ring_step_s": round(step_s, 3),
        "mirror_first_call_s": round(mirror_s, 1),
        "shapes": {"b": b, "h": h, "n": args.n, "dh": dh, "kk": kk},
    }
    ok_kernel = graph_identical and max_abs < 2e-5

    # ---- 2. end-to-end train step at the registered long config ----------
    from csat_tpu.parallel.dryrun import dryrun_train_step, tiny_multichip_config

    base = tiny_multichip_config(8, data=2, model_par=1, seq_par=4).replace(
        max_src_len=args.n, noise_mode="counter", remat=True,
        attention_dropout=0.0,
    )
    t0 = time.perf_counter()
    loss_ag, _ = dryrun_train_step(8, model_par=1, seq_par=4, cfg=base)
    ag_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    loss_ring, info = dryrun_train_step(
        8, model_par=1, seq_par=4, cfg=base.replace(seq_impl="ring"))
    ring_s = time.perf_counter() - t0
    report["train_step"] = {
        "loss_allgather": round(float(loss_ag), 6),
        "loss_ring": round(float(loss_ring), 6),
        "abs_diff": round(abs(float(loss_ring) - float(loss_ag)), 6),
        "mesh": info["mesh"],
        "remat": True,
        "allgather_wall_s": round(ag_s, 1),
        "ring_wall_s": round(ring_s, 1),
    }
    ok_e2e = abs(float(loss_ring) - float(loss_ag)) < 1e-3
    report["ok"] = bool(ok_kernel and ok_e2e)

    if args.out:
        os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
        with open(args.out, "w") as f:
            json.dump(report, f, indent=1)
    print(json.dumps(report))
    sys.exit(0 if report["ok"] else 1)


if __name__ == "__main__":
    main()

"""Run the PE probe (intermediate-node prediction) on a trained model.

Parity entry point for the reference's ``inp_py.py`` / ``inp_java.py``
experiments: for each hop count (3/5/7, ref ``inp_py.py:56-90``) sample
node pairs that many edges apart in the test-set ASTs, take the
post-expansion PE the encoder produced for the pair, and fit an MLP to
predict the middle node's token id.

    python tools/run_probe.py --config python --data_dir ./data \
        [--checkpoint_dir outputs/...] [--hops 3 5 7]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import numpy as np


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", default="python")
    ap.add_argument("--data_dir", default=None)
    ap.add_argument("--split", default="test")
    ap.add_argument("--checkpoint_dir", default=None)
    ap.add_argument("--hops", type=int, nargs="+", default=[3, 5, 7])
    ap.add_argument("--max_samples", type=int, default=256)
    ap.add_argument("--override", action="append", default=[],
                    help="config override key=value (repeatable) — must match "
                         "the dims the checkpoint was trained with, e.g. "
                         "--override hidden_size=128 --override num_heads=8")
    ap.add_argument("--out", default="", help="optional JSON output path")
    ap.add_argument("--platform", default="cpu",
                    help="jax platform (default cpu — the probe is cheap and "
                         "must not touch a wedged TPU relay; pass '' to use "
                         "the ambient backend)")
    args = ap.parse_args()
    if args.platform:
        # the axon plugin ignores the env var; the config update is the
        # reliable off-switch (jax imported at module top)
        jax.config.update("jax_platforms", args.platform)

    from csat_tpu.configs import get_config
    from csat_tpu.data.dataset import ASTDataset, iterate_batches, load_matrices
    from csat_tpu.data.vocab import load_vocab
    from csat_tpu.probe import extract_pe, run_probe
    from csat_tpu.train.checkpoint import restore_params
    from csat_tpu.train.state import create_train_state, default_optimizer, make_model

    import ast as _ast

    overrides = {}
    if args.data_dir:
        overrides["data_dir"] = args.data_dir
    for kv in args.override:
        k, v = kv.split("=", 1)
        try:
            overrides[k] = _ast.literal_eval(v)
        except (ValueError, SyntaxError):
            overrides[k] = v
    cfg = get_config(args.config, **overrides)
    src_vocab, tgt_vocab = load_vocab(cfg.data_dir)
    ds = ASTDataset(cfg, args.split, src_vocab, tgt_vocab)
    mats = load_matrices(os.path.join(cfg.data_dir, args.split, "split_matrices.npz"))
    records = mats["root_first_seq"]

    model = make_model(cfg, src_vocab.size(), tgt_vocab.size(), 2048)
    first = next(iterate_batches(ds, cfg.batch_size, shuffle=False, drop_last=False))
    state = create_train_state(model, default_optimizer(cfg), first, seed=0)
    params = state.params
    if args.checkpoint_dir:
        params = restore_params(args.checkpoint_dir)

    sequential = cfg.use_pegen == "sequential"
    if sequential:
        # the sequential variant has no learned probe-visible PE; the
        # reference probes the raw sinusoidal encoding directly
        # (ref inp_py.py:464 comment + :618-722 section)
        from csat_tpu.models.components import sinusoidal_table

        sin_pe = np.asarray(
            sinusoidal_table(cfg.max_src_len, cfg.sbm_enc_dim))

    pes, parents, n_nodes, types = [], [], [], []
    key = jax.random.key(0)
    seen = 0
    for batch in iterate_batches(ds, cfg.batch_size, shuffle=False, drop_last=False):
        key, sub = jax.random.split(key)
        if sequential:
            pe = np.broadcast_to(
                sin_pe[None], (batch.src_seq.shape[0], *sin_pe.shape))
        else:
            pe = extract_pe(model, params, batch, sub)  # (B, N, pe_dim)
        for b in range(pe.shape[0]):
            if seen >= min(args.max_samples, len(records)):
                break
            rec = records[seen]
            n = min(int(batch.num_node[b]), len(rec.parent_idx))
            pes.append(pe[b])
            parents.append(np.maximum(rec.parent_idx[:n], 0))
            n_nodes.append(n)
            types.append(np.asarray(batch.src_seq[b]))
            seen += 1
        if seen >= min(args.max_samples, len(records)):
            break

    pes_arr = np.stack(pes)
    results = [
        run_probe(pes_arr, parents, n_nodes, types, hops=h, epochs=100)
        for h in args.hops
    ]
    report = {"config": cfg.name, "split": args.split,
              "checkpoint": args.checkpoint_dir, "overrides": overrides,
              "probe": results}
    if args.out:
        os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
        with open(args.out, "w") as f:
            json.dump(report, f, indent=1)
    print(json.dumps(report))


if __name__ == "__main__":
    main()

"""Live fleet console over the serve telemetry artifacts (ISSUE 14).

``csat_tpu top`` tails the metrics JSONL the serve CLI writes
(``--metrics_file``) and repaints one screen per refresh:

* fleet header — healthy/target replicas, capacity fraction, fleet queue
  depth and busy slots (or the single-engine equivalents);
* per-replica table — health state, outcome counters, queue, busy slots
  and mean latency (reuses ``tools/obs_report.py``'s fleet table);
* KV page occupancy per replica (pages in use / usable);
* SLO burn — per objective the fast- and slow-window burn rates and
  whether the alert is firing (``csat_tpu/obs/slo.py`` gauges);
* the slowest recent request traces when a trace dump
  (``--traces_file``) is available.

Everything is read from files — the console never touches a live engine,
so it can run on another host against a tailed/copied artifact.

Usage::

    csat_tpu top --metrics serve_metrics.jsonl --traces serve_traces.jsonl
    python tools/serve_top.py --metrics serve_metrics.jsonl --once
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import List, Optional, Tuple

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tools.obs_report import (  # noqa: E402
    _fmt_table,
    fleet_table,
    kv_pages_table,
    net_table,
    split_fleet_snapshot,
    trace_lines,
)

_CLEAR = "\x1b[2J\x1b[H"


def last_snapshot(path: str) -> Tuple[dict, int]:
    """(last snapshot, total snapshot count) from a metrics JSONL file —
    re-read per refresh so the console follows a file being appended to."""
    snap: dict = {}
    n = 0
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    snap = json.loads(line)
                    n += 1
                except ValueError:
                    continue  # torn tail line mid-append — keep previous
    except OSError:
        return {}, 0
    return snap, n


def _g(snap: dict, key: str, default=0):
    v = snap.get(key)
    return default if v is None else v


def header_lines(snap: dict, n_snaps: int) -> List[str]:
    t = snap.get("t")
    stamp = (time.strftime("%H:%M:%S", time.localtime(t))
             if isinstance(t, (int, float)) else "?")
    out = [f"csat_tpu top — snapshot {n_snaps} @ {stamp}"]
    if "fleet_capacity_frac" in snap:
        out.append(
            f"  fleet: {_g(snap, 'fleet_healthy_replicas')}"
            f"/{_g(snap, 'fleet_target_replicas')} healthy  "
            f"capacity {_g(snap, 'fleet_capacity_frac', 1.0):.2f}  "
            f"queue {_g(snap, 'fleet_queue_depth')}  "
            f"busy {_g(snap, 'fleet_slots_occupied')}  "
            f"resubmissions {_g(snap, 'fleet_resubmissions_total')}  "
            f"spawned {_g(snap, 'fleet_replicas_spawned_total')}")
    else:
        mesh = _g(snap, "serve_mesh_devices", 1)
        out.append(
            f"  engine: queue {_g(snap, 'serve_queue_depth')}  "
            f"busy {_g(snap, 'serve_slots_occupied')}  "
            f"ok {_g(snap, 'serve_requests_ok_total')}  "
            f"shed {_g(snap, 'serve_requests_shed_total')}  "
            f"gen_tokens {_g(snap, 'serve_gen_tokens_total')}"
            + (f"  mesh_chips {mesh}" if mesh > 1 else ""))
    return out


def pages_lines(snaps: List[dict]) -> List[str]:
    """KV page occupancy per replica: HBM in-use / usable / peak, plus the
    host/disk tier residency columns whenever a replica serves with the
    tiered store (shared renderer with ``tools/obs_report.py``, which is
    where the column set lives).  Rectangle-layout replicas (0 usable
    pages) are skipped."""
    table = kv_pages_table(snaps)
    if not table:
        return []
    return ["== kv pages ==", *table.splitlines()]


def net_lines(snaps: List[dict]) -> List[str]:
    """Network front-door connection/stall/resume columns (ISSUE 20) —
    shown whenever a replica serves behind ``--net`` (shared renderer
    with ``tools/obs_report.py``)."""
    table = net_table(snaps)
    if not table:
        return []
    return ["== net front door ==", *table.splitlines()]


def slo_lines(snap: dict) -> List[str]:
    """Burn-rate table + active alerts from the ``slo_*`` gauges the SLO
    engine writes into the scrape registry."""
    names = sorted(k[len("slo_burn_fast_"):] for k in snap
                   if k.startswith("slo_burn_fast_"))
    if not names:
        return []
    rows = []
    firing = []
    for name in names:
        alert = _g(snap, f"slo_alert_{name}")
        if alert:
            firing.append(name)
        rows.append((name,
                     f"{_g(snap, f'slo_burn_fast_{name}', 0.0):.2f}",
                     f"{_g(snap, f'slo_burn_slow_{name}', 0.0):.2f}",
                     "FIRING" if alert else "ok"))
    out = ["== slo burn ==",
           *_fmt_table(rows, ("objective", "fast", "slow", "alert"))
           .splitlines()]
    out.append("active alerts: " + (", ".join(firing) if firing else "none"))
    return out


def render(metrics_path: str, traces_path: str = "",
           slowest: int = 5) -> str:
    """One full console frame as a string (main() repaints it)."""
    snap, n_snaps = last_snapshot(metrics_path)
    if not snap:
        return f"(no snapshots yet in {metrics_path})"
    lines = header_lines(snap, n_snaps)
    replicas = split_fleet_snapshot(snap)
    if replicas:
        lines += ["", "== replicas =="]
        lines += fleet_table(replicas).splitlines()
        pages = pages_lines(replicas)
        if pages:
            lines += [""] + pages
        net = net_lines(replicas)
        if net:
            lines += [""] + net
    else:
        pages = pages_lines([snap])
        if pages:
            lines += [""] + pages
        net = net_lines([snap])
        if net:
            lines += [""] + net
    slo = slo_lines(snap)
    if slo:
        lines += [""] + slo
    if traces_path and os.path.exists(traces_path):
        lines += [""] + trace_lines(traces_path, slowest)
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--metrics", required=True,
                   help="metrics JSONL the serve CLI writes "
                        "(--metrics_file)")
    p.add_argument("--traces", default="",
                   help="request-trace dump JSONL (--traces_file); "
                        "renders the slowest recent traces")
    p.add_argument("--interval", type=float, default=2.0,
                   help="refresh period in seconds")
    p.add_argument("--once", action="store_true",
                   help="render one frame and exit (no screen clearing) — "
                        "what the tests and scripts use")
    p.add_argument("--slowest", type=int, default=5,
                   help="how many of the slowest traces to show")
    args = p.parse_args(argv)
    try:
        if args.once:
            print(render(args.metrics, args.traces, args.slowest))
            return 0
        while True:
            frame = render(args.metrics, args.traces, args.slowest)
            sys.stdout.write(_CLEAR + frame + "\n")
            sys.stdout.flush()
            time.sleep(max(args.interval, 0.1))
    except KeyboardInterrupt:
        return 0
    except BrokenPipeError:
        # `csat_tpu top --once | head` closing the pipe is a clean exit
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        return 0


if __name__ == "__main__":
    sys.exit(main())

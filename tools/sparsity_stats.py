"""Measured tile-skip rate of a TRAINED model (VERDICT r3 next-round #2).

The flash kernel skips a (q-tile, k-tile) block's matmuls when its sampled
graph block is all-zero. The synthetic test (`tests/test_flash_ops.py`)
proves the mechanism; this tool measures whether a REAL trained model's
memberships actually produce dead tiles — the datum the ≥4× bet rides on.

Loads a checkpoint, runs the XLA aux forward (which returns the sampled
graphs — bit-comparable to the kernel's in-kernel sampling) over real test
batches, and reports per-layer tile deadness at the checkpoint's training
floor AND at the reference floor for contrast (same params; the floor only
changes the Bernoulli clamp).

    python tools/sparsity_stats.py \
        --checkpoint_dir outputs/r4/final_exp/real_stdlib_sbm_floor0 \
        --data_dir ./data/stdlib_python --out results/perf/tile_skip_r4.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

TILE = 128


def tile_deadness(graph: np.ndarray, pad: np.ndarray, tile: int = TILE) -> tuple:
    """(dead_tiles, total_tiles) over tile-aligned blocks of a (B,H,N,N)
    sampled graph; padded keys cannot carry mass (the kernel's a_eff)."""
    b, h, n, _ = graph.shape
    eff = graph * (1.0 - pad[:, None, None, :])
    n_pad = ((n + tile - 1) // tile) * tile
    gpad = np.zeros((b, h, n_pad, n_pad), graph.dtype)
    gpad[:, :, :n, :n] = eff
    t = n_pad // tile
    blocks = gpad.reshape(b, h, t, tile, t, tile).sum(axis=(3, 5))
    dead = int((blocks == 0).sum())
    return dead, b * h * t * t


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--checkpoint_dir", required=True)
    ap.add_argument("--data_dir", required=True)
    ap.add_argument("--batches", type=int, default=2)
    ap.add_argument("--batch_size", type=int, default=16)
    ap.add_argument("--out", default="")
    ap.add_argument("--override", action="append", default=[])
    args = ap.parse_args()

    import ast as _ast

    from csat_tpu.configs import get_config
    from csat_tpu.data.dataset import ASTDataset, iterate_batches
    from csat_tpu.data.vocab import load_vocab
    from csat_tpu.train.checkpoint import restore_params
    from csat_tpu.train.state import make_model

    overrides = {
        "data_dir": args.data_dir, "batch_size": args.batch_size,
        # train_real CPU dims — override via --override for other runs
        "pe_dim": 64, "pegen_dim": 128, "sbm_enc_dim": 128,
        "hidden_size": 128, "num_heads": 4, "num_layers": 2,
        "sbm_layers": 2, "clusters": (8, 8), "dim_feed_forward": 512,
        "max_tgt_len": 30,
    }
    for kv in args.override:
        k, v = kv.split("=", 1)
        try:
            overrides[k] = _ast.literal_eval(v)
        except (ValueError, SyntaxError):
            overrides[k] = v

    sv, tv = load_vocab(args.data_dir)
    params = restore_params(args.checkpoint_dir)

    report = {"checkpoint": args.checkpoint_dir, "floors": {}}
    for floor in (0.0, 0.01):
        cfg = get_config("python", sbm_floor=floor, **overrides)
        model = make_model(cfg, sv.size(), tv.size())
        ds = ASTDataset(cfg, "test", sv, tv)
        dead_by_layer, total_by_layer, density = None, None, []
        finer = {32: [0, 0], 64: [0, 0]}  # skip headroom at smaller tiles
        key = jax.random.key(0)
        for bi, batch in enumerate(
                iterate_batches(ds, cfg.batch_size, shuffle=False)):
            if bi >= args.batches:
                break
            key, sub = jax.random.split(key)
            _, _, _, graphs, _ = model.apply(
                {"params": params}, batch, deterministic=True,
                collect_aux=True, rngs={"sample": sub})
            pad = np.asarray(batch.src_seq == 0, np.float32)
            if dead_by_layer is None:
                dead_by_layer = [0] * len(graphs)
                total_by_layer = [0] * len(graphs)
            for li, g in enumerate(graphs):
                g = np.asarray(g, np.float32)
                d, t = tile_deadness(g, pad)
                dead_by_layer[li] += d
                total_by_layer[li] += t
                density.append(float(g.mean()))
                for ft in finer:
                    fd, ftt = tile_deadness(g, pad, ft)
                    finer[ft][0] += fd
                    finer[ft][1] += ftt
        report["floors"][str(floor)] = {
            "dead_tiles_by_layer": dead_by_layer,
            "total_tiles_by_layer": total_by_layer,
            "skip_rate_by_layer": [
                round(d / t, 4) for d, t in zip(dead_by_layer, total_by_layer)],
            "skip_rate_overall": round(
                sum(dead_by_layer) / sum(total_by_layer), 4),
            "mean_edge_density": round(float(np.mean(density)), 4),
            "skip_rate_tile32": round(finer[32][0] / finer[32][1], 4),
            "skip_rate_tile64": round(finer[64][0] / finer[64][1], 4),
        }

    print(json.dumps(report))
    if args.out:
        os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
        with open(args.out, "w") as f:
            json.dump(report, f, indent=1)


if __name__ == "__main__":
    main()

"""Step-0 loss decomposition: where does the ported-init forward differ?

Companion to tools/lockstep_ab.py. Runs ONE real padded batch through both
frameworks at identical (ported) params with shared Bernoulli noise, and
prints (nll, sparsity) per framework — then repeats with the torch PAD
embedding rows zeroed, to attribute the delta to the reference's frozen
garbage-PAD-row quirk (torch ``padding_idx=0`` + global xavier re-init,
ref ``csa_trans.py:166-168`` + ``components.py:28``).
"""

from __future__ import annotations

import importlib.util
import json
import os
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
import torch  # noqa: E402

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp  # noqa: E402

from tools.train_torch_real import _import_reference, _to_torch  # noqa: E402

ref_module, ref_utils, ref_optimizer = _import_reference()

spec = importlib.util.spec_from_file_location(
    "parity_helpers", os.path.join(REPO, "tests", "test_reference_parity.py"))
ph = importlib.util.module_from_spec(spec)
spec.loader.exec_module(ph)

from csat_tpu.configs import get_config  # noqa: E402
from csat_tpu.data.dataset import ASTDataset, iterate_batches  # noqa: E402
from csat_tpu.data.vocab import load_vocab  # noqa: E402
from csat_tpu.train.loss import label_smoothing_loss  # noqa: E402
from csat_tpu.train.state import make_model  # noqa: E402

cfg = get_config(
    "python", data_dir="./data/stdlib_python", batch_size=32,
    pe_dim=64, pegen_dim=128, sbm_enc_dim=128, hidden_size=128,
    num_heads=8, num_layers=2, sbm_layers=2, clusters=(8, 8),
    dim_feed_forward=512, max_tgt_len=30,
)
src_vocab, tgt_vocab = load_vocab(cfg.data_dir)
train_ds = ASTDataset(cfg, "train", src_vocab, tgt_vocab)
batch = next(iterate_batches(train_ds, cfg.batch_size, shuffle=True,
                             seed=cfg.seed + 1))

torch.manual_seed(cfg.seed)
tmodel = ref_module.csa_trans.CSATrans(
    src_vocab_size=src_vocab.size(), tgt_vocab_size=tgt_vocab.size(),
    hidden_size=cfg.hidden_size, num_heads=cfg.num_heads,
    num_layers=cfg.num_layers, sbm_layers=cfg.sbm_layers,
    use_pegen="pegen", dim_feed_forward=cfg.dim_feed_forward,
    dropout=cfg.dropout, pe_dim=cfg.pe_dim, pegen_dim=cfg.pegen_dim,
    sbm_enc_dim=cfg.sbm_enc_dim, clusters=list(cfg.clusters),
    full_att=False, max_src_len=cfg.max_src_len,
)
tmodel.eval()
criterion = ref_utils.label_smooth.LabelSmoothing(padding_idx=0,
                                                  smoothing=cfg.smoothing)

b, h, n = cfg.batch_size, cfg.num_heads, cfg.max_src_len
noises = [np.random.default_rng(5).uniform(size=(b, h, n, n)).astype(np.float32)
          for _ in range(cfg.sbm_layers)]

_tnoise = []
torch.bernoulli = lambda t: (torch.from_numpy(_tnoise.pop(0)) < t).float()

import csat_tpu.models.sbm as sbm_mod  # noqa: E402

_joverride = []
sbm_mod.bernoulli_noise = lambda key, shape: jnp.asarray(_joverride.pop(0))


def torch_fwd():
    _tnoise[:] = [x.copy() for x in noises]
    d, target = _to_torch(batch, torch)
    with torch.no_grad():
        out, sp, _, _, _ = tmodel(d)
        nll = criterion(out.reshape(-1, out.size(-1)), target.reshape(-1))
    return float(nll), float(sp)


def full_params(sd):
    return {
        "src_embedding": ph._emb(sd, "src_embedding"),
        "tgt_embedding": ph._emb(sd, "tgt_embedding"),
        "src_pe_embedding": ph._emb(sd, "src_pe_embedding"),
        "pegen": ph.cse_params(sd, cfg.num_layers),
        "encoder": ph.sbm_params(sd, cfg.sbm_layers),
        "decoder": ph.decoder_params(sd, cfg.decoder_layers, cfg.hidden_size),
        "generator": {"Dense_0": ph._lin(sd, "generator.linear")},
    }


fmodel = make_model(cfg, src_vocab.size(), tgt_vocab.size())


def jax_fwd(params):
    _joverride[:] = [x.copy() for x in noises]
    log_probs, sp, _, _, _ = fmodel.apply(
        {"params": params}, batch, deterministic=True,
        rngs={"sample": jax.random.key(0)})
    nll = label_smoothing_loss(log_probs, batch.target, cfg.smoothing)
    return float(nll), float(sp)


t_nll, t_sp = torch_fwd()
params = jax.tree.map(jnp.asarray, full_params(tmodel.state_dict()))
j_nll, j_sp = jax_fwd(params)
print(json.dumps({"torch": {"nll": t_nll, "sparsity": t_sp},
                  "jax": {"nll": j_nll, "sparsity": j_sp}}))

# pad_row="frozen" on the SAME garbage-row params must match orig torch
cfg_frozen = cfg.replace(pad_row="frozen")
fmodel_frozen = make_model(cfg_frozen, src_vocab.size(), tgt_vocab.size())
_joverride[:] = [x.copy() for x in noises]
log_probs, sp_f, _, _, _ = fmodel_frozen.apply(
    {"params": params}, batch, deterministic=True,
    rngs={"sample": jax.random.key(0)})
j_nll_f = float(label_smoothing_loss(log_probs, batch.target, cfg.smoothing))
print(json.dumps({"jax_frozen": {"nll": j_nll_f, "sparsity": float(sp_f)},
                  "delta_vs_torch": round(abs(j_nll_f - t_nll), 8)}))

# zero the PAD rows in torch (src, src_pe, tgt) and re-run both
with torch.no_grad():
    for emb in (tmodel.src_embedding, tmodel.src_pe_embedding,
                tmodel.tgt_embedding):
        emb.word_embeddings.weight[0].zero_()
t_nll0, t_sp0 = torch_fwd()
params0 = jax.tree.map(jnp.asarray, full_params(tmodel.state_dict()))
j_nll0, j_sp0 = jax_fwd(params0)
print(json.dumps({"pad_zeroed": {"torch": {"nll": t_nll0, "sparsity": t_sp0},
                                 "jax": {"nll": j_nll0, "sparsity": j_sp0}}}))

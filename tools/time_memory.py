"""Forward / forward+backward wall-time and peak-memory harness.

Capability parity with ``/root/reference/csa_trans_time_memory.py:88-158``,
which defines the repo's perf protocol: 20 repetitions of (a) forward-only
and (b) forward+backward sweeps over a fixed batch stream, reporting wall
seconds and peak device memory.

TPU translation: ``torch.cuda.Event`` timing → ``block_until_ready`` around
jitted calls; ``memory_stats()["allocated_bytes.all.peak"]`` →
``device.memory_stats()["peak_bytes_in_use"]``. On CPU the backend exposes
no stats, so two best-effort bounds are recorded instead: ``*_live_gb``
(sum of live device buffers after the sweep — a floor: residents only) and
``*_host_rss_peak_gb`` (process peak RSS — a ceiling: includes the Python
runtime; monotone across sweeps).

    python tools/time_memory.py [--config python] [--backend pallas]
                                [--batch 64] [--reps 20] [--steps 8]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax


def peak_bytes() -> int:
    try:
        stats = jax.devices()[0].memory_stats() or {}
        return int(stats.get("peak_bytes_in_use", 0))
    except Exception:
        return 0


from tools.xla_util import xla_mem  # noqa: E402  (shared with bench.py)


def live_bytes() -> int:
    """Sum of currently-live device buffers — a best-effort floor for CPU,
    where the backend exposes no ``memory_stats()``. Captures residents
    (params, opt state, batches, last outputs) but NOT transient peaks
    inside a step; the host-RSS peak below bounds those from above."""
    try:
        return sum(int(x.nbytes) for x in jax.live_arrays())
    except Exception:
        return 0


def host_rss_peak_bytes() -> int:
    """Process-lifetime peak RSS (linux ru_maxrss is KiB). Monotone over the
    run, so the fwd-sweep reading is a valid bound for the fwd phase and the
    final reading bounds fwd+bwd; includes Python/runtime overhead."""
    try:
        import resource

        return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
    except Exception:
        return 0


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", default="python")
    ap.add_argument("--backend", default="")
    ap.add_argument("--compute_dtype", default="")
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--reps", type=int, default=20)
    ap.add_argument("--steps", type=int, default=8, help="batches per rep")
    ap.add_argument("--platform", default="",
                    help="force a jax platform (e.g. cpu) pre-backend-init")
    ap.add_argument("--max_src_len", type=int, default=0,
                    help="override AST length N (0 = config default)")
    ap.add_argument("--remat", default="",
                    help="'1'/'0' to override cfg.remat (''=config default)")
    ap.add_argument("--noise_mode", default="",
                    help="override noise_mode (counter routes pallas to the "
                         "flash kernel; shared to the fused kernel)")
    ap.add_argument("--floor", default="",
                    help="sbm_floor override ('0.0' lifts the reference's "
                         "0.01 Bernoulli clamp so the flash kernel's "
                         "data-dependent tile skip can fire)")
    args = ap.parse_args()
    if args.platform:
        # jax is already imported at module top, so only the config update
        # takes effect in-process (the env var would be a no-op here)
        jax.config.update("jax_platforms", args.platform)

    from csat_tpu.configs import get_config
    from csat_tpu.data.toy import random_batch
    from csat_tpu.train.loop import make_train_step
    from csat_tpu.train.state import create_train_state, default_optimizer, make_model

    overrides = {"batch_size": args.batch}
    if args.backend:
        overrides["backend"] = args.backend
    if args.compute_dtype:
        overrides["compute_dtype"] = args.compute_dtype
    if args.max_src_len:
        overrides["max_src_len"] = args.max_src_len
    if args.remat:
        overrides["remat"] = args.remat == "1"
    if args.noise_mode:
        overrides["noise_mode"] = args.noise_mode
    if args.floor:
        overrides["sbm_floor"] = float(args.floor)
    cfg = get_config(args.config, **overrides)
    src_v, tgt_v, trip_v = 10_000, 20_000, 1246
    batches = [
        jax.tree.map(jax.device_put, random_batch(cfg, cfg.batch_size, src_v, tgt_v, trip_v, seed=s))
        for s in range(args.steps)
    ]
    model = make_model(cfg, src_v, tgt_v, trip_v)
    tx = default_optimizer(cfg)
    state = create_train_state(model, tx, batches[0], seed=cfg.seed)
    step = make_train_step(model, tx, cfg)

    @jax.jit
    def fwd(params, batch, key):
        log_probs, sparsity, _, _, _ = model.apply(
            {"params": params}, batch, rngs={"sample": key}
        )
        return log_probs, sparsity

    key = jax.random.key(0)

    # --- forward-only sweep (ref :103-125) ---
    # AOT-compile once: the SAME executable serves the sweep and the static
    # memory analysis (a separate .lower().compile() would double compile
    # cost at N=512 and could analyze a different schedule)
    fwd_c = fwd.lower(state.params, batches[0], key).compile()
    fwd_mem = xla_mem(fwd_c)
    jax.block_until_ready(fwd_c(state.params, batches[0], key))  # warmup
    fwd_times = []
    for _ in range(args.reps):
        t0 = time.perf_counter()
        for b in batches:
            out = fwd_c(state.params, b, key)
        jax.block_until_ready(out)
        fwd_times.append(time.perf_counter() - t0)
    fwd_peak = peak_bytes()
    fwd_live = live_bytes()
    fwd_rss = host_rss_peak_bytes()

    # --- forward+backward sweep (ref :129-149) ---
    step_c = step.lower(state, batches[0]).compile()
    fb_mem = xla_mem(step_c)
    state, m = step_c(state, batches[0])  # warmup
    jax.block_until_ready(m["loss"])
    fb_times = []
    for _ in range(args.reps):
        t0 = time.perf_counter()
        for b in batches:
            state, m = step_c(state, b)
        jax.block_until_ready(m["loss"])
        fb_times.append(time.perf_counter() - t0)
    fb_peak = peak_bytes()
    fb_live = live_bytes()
    fb_rss = host_rss_peak_bytes()

    nodes = cfg.batch_size * cfg.max_src_len * args.steps
    result = {
        "config": cfg.name,
        "backend": cfg.backend,
        "compute_dtype": cfg.compute_dtype,
        "max_src_len": cfg.max_src_len,
        "noise_mode": cfg.noise_mode,
        "sbm_floor": cfg.sbm_floor,
        "remat": cfg.remat,
        "batch": cfg.batch_size,
        "device": str(jax.devices()[0]),
        "fwd_sec_mean": round(sum(fwd_times) / len(fwd_times), 4),
        "fwd_sec_min": round(min(fwd_times), 4),
        "fwd_peak_gb": round(fwd_peak / 2**30, 3),
        "fwd_live_gb": round(fwd_live / 2**30, 3),
        "fwd_host_rss_peak_gb": round(fwd_rss / 2**30, 3),
        "fwd_xla": fwd_mem,
        "fwdbwd_sec_mean": round(sum(fb_times) / len(fb_times), 4),
        "fwdbwd_sec_min": round(min(fb_times), 4),
        "fwdbwd_peak_gb": round(fb_peak / 2**30, 3),
        "fwdbwd_live_gb": round(fb_live / 2**30, 3),
        "fwdbwd_host_rss_peak_gb": round(fb_rss / 2**30, 3),
        "fwdbwd_xla": fb_mem,
        "fwd_nodes_per_sec": round(nodes / min(fwd_times), 1),
        "fwdbwd_nodes_per_sec": round(nodes / min(fb_times), 1),
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()

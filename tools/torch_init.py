"""Port an ACTUAL torch-reference initialization into flax params.

VERDICT r4 #2(b): two systematic init-distribution differences separate
the frameworks even though both say "xavier":

* torch ``nn.MultiheadAttention`` packs q/k/v into one (3d, d)
  ``in_proj_weight``; the reference's global ``xavier_uniform_`` sees fan
  (3d, d) → bound √(6/4d), i.e. the decoder attention projections start
  √2 SMALLER than flax's per-matrix xavier on (d, d);
* torch ``nn.Linear`` bias init is uniform(±1/√fan_in) and the xavier
  loop only touches dim>1 tensors, so every reference Linear bias starts
  nonzero — flax biases start at zero.

Rather than approximating those distributions, this helper builds the
reference model itself at the paired dims (imported from
``/root/reference`` at runtime — nothing copied), seeds torch with
``cfg.seed``, and converts the resulting state_dict with the parity-test
converters. The returned tree is real NumPy copies (no aliasing of torch
storage — the zero-copy hazard tools/lockstep_ab.py documents).
"""

from __future__ import annotations

import importlib.util
import os
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

__all__ = ["torch_reference_init"]


def torch_reference_init(cfg, src_vocab_size: int, tgt_vocab_size: int):
    """→ flax params pytree holding the torch reference's init at cfg.seed."""
    assert cfg.num_heads == 8, (
        "the reference CSE hard-tiles 4 L-heads + 4 T-heads "
        "(csa_trans.py:206-211); init porting requires num_heads=8")
    from tools.pair_common import build_reference_model, import_reference

    ref_module, _, _ = import_reference()
    spec = importlib.util.spec_from_file_location(
        "parity_helpers", os.path.join(REPO, "tests", "test_reference_parity.py"))
    ph = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(ph)

    tmodel = build_reference_model(
        ref_module, cfg, src_vocab_size, tgt_vocab_size)
    sd = tmodel.state_dict()
    params = {
        "src_embedding": ph._emb(sd, "src_embedding"),
        "tgt_embedding": ph._emb(sd, "tgt_embedding"),
        "src_pe_embedding": ph._emb(sd, "src_pe_embedding"),
        "pegen": ph.cse_params(sd, cfg.num_layers),
        "encoder": ph.sbm_params(sd, cfg.sbm_layers, full_att=cfg.full_att),
        "decoder": ph.decoder_params(sd, cfg.decoder_layers, cfg.hidden_size),
        "generator": {"Dense_0": ph._lin(sd, "generator.linear")},
    }
    import jax

    return jax.tree.map(lambda a: np.array(a, copy=True), params)

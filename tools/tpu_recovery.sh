#!/bin/bash
# TPU-recovery measurement sequence (run the moment `bench.py --probe`
# answers — the first healthy window may be the only one; see
# results/perf/tpu_session_r3.md for the claim rules this encodes).
#
# One chip claim per child, clean exits, warm .jax_cache between stages.
# Usage:  bash tools/tpu_recovery.sh [results_dir]
set -u
cd "$(dirname "$0")/.."
OUT=${1:-results/perf}
mkdir -p "$OUT"
STAMP=$(date -u +%Y%m%dT%H%M%SZ)
LOG="$OUT/tpu_recovery_$STAMP.log"
say() { echo "[$(date -u +%T)] $*" | tee -a "$LOG"; }

say "probe"
timeout 150 python bench.py --probe >> "$LOG" 2>&1 || { say "probe dead rc=$?"; exit 1; }

# 1. bench variants, proven-first, ONE serve child per variant so an
#    overrun never takes later variants down with it (soft budget 900 s,
#    first compiles can exceed 600 s through the remote compiler)
for SPEC in pallas:float32:default:64:20 xla:float32:default:64:20 \
            xla:bfloat16:default:64:20 pallas:bfloat16:default:64:20; do
  say "serve $SPEC"
  timeout 1100 python bench.py --serve "$SPEC" 900 >> "$LOG" 2>&1
  say "serve $SPEC rc=$? (results in .bench_results.jsonl)"
  timeout 150 python bench.py --probe >> "$LOG" 2>&1 || { say "relay died after $SPEC"; break; }
done
cp -f .bench_results.jsonl "$OUT/bench_results_tpu_$STAMP.jsonl" 2>/dev/null

# 2. time/memory matrix on-chip (real peak HBM per N/remat/kernel combo)
say "memory matrix (tpu)"
timeout 5400 python tools/memory_matrix.py --device tpu \
  --out "$OUT/memory_matrix_tpu_$STAMP.jsonl" >> "$LOG" 2>&1
say "memory matrix rc=$?"

# 3. pallas-vs-xla step time at the sparsity floors (the block-skip bet)
for ARGS in "--backend pallas --noise_mode counter" \
            "--backend xla --noise_mode counter"; do
  for FLOOR_CFG in "" "--max_src_len 512"; do
    say "time_memory $ARGS $FLOOR_CFG"
    timeout 1500 python tools/time_memory.py --config python $ARGS $FLOOR_CFG \
      --batch 64 --reps 5 --steps 4 >> "$LOG" 2>&1
  done
done

# 4. full-dims real-data training on the chip (background; runs as long as
#    the window lasts — resume-capable via orbax)
say "launching full-dims train_real on axon"
nohup python tools/train_real.py --data_dir ./data/stdlib_python \
  --variant sbm --full_dims --backend pallas --platform axon \
  --epochs 40 --val_interval 5 --out ./outputs/real_stdlib_tpu \
  > "$OUT/train_tpu_$STAMP.log" 2>&1 &
say "done (train pid $!)"

#!/bin/bash
# TPU-recovery measurement sequence (run the moment the relay answers —
# the first healthy window may be the only one; see
# results/perf/tpu_session_r3.md and _r4.md for the claim rules this
# encodes).
#
# Claim rules: one chip claim per child, clean exits, warm .jax_cache
# between stages. `timeout`'s SIGTERM cannot stop a child stuck inside a
# native compile RPC (observed r4: the handler never runs while the main
# thread polls the relay socket), so after every stage we check the relay
# at TCP level (tools/relay_probe.py — claim-free) and BAIL Out if it is
# gone instead of cascading more claimants into a dead tunnel. A stuck
# child is SIGKILLed only when the relay is already dead (nothing left to
# wedge); while the relay lives we always wait.
#
# Usage:  bash tools/tpu_recovery.sh [results_dir]
set -u
cd "$(dirname "$0")/.."
OUT=${1:-results/perf}
mkdir -p "$OUT"
STAMP=$(date -u +%Y%m%dT%H%M%SZ)
LOG="$OUT/tpu_recovery_$STAMP.log"
say() { echo "[$(date -u +%T)] $*" | tee -a "$LOG"; }

relay_up() { python tools/relay_probe.py --quiet; }

# Run "$@" under a hard cap (first arg = seconds). If the cap fires and the
# child survives SIGTERM (native-stuck), SIGKILL it IFF the relay is dead.
# Diagnostics go straight to $LOG (never stdout: several callers redirect
# run_capped's stdout into JSONL results files).
diag() { echo "[$(date -u +%T)] $*" >> "$LOG"; }

run_capped() {
  local cap=$1; shift
  "$@" &
  local pid=$!
  local t=0
  local termed=0
  while kill -0 "$pid" 2>/dev/null; do
    sleep 15; t=$((t + 15))
    if [ "$t" -ge "$cap" ]; then
      if relay_up; then
        # over budget but the tunnel lives: request a clean exit ONCE (the
        # child's SIGTERM handler emits evidence + releases its claim when
        # it next reaches Python; a second TERM mid-handler would abort
        # that cleanup) and KEEP WAITING — SIGKILLing a live claimant is
        # the documented wedge mechanism, and while it holds the claim no
        # later stage could run anyway.
        if [ "$termed" -eq 0 ]; then
          kill -TERM "$pid" 2>/dev/null
          termed=1
          diag "  over cap at ${t}s — sent SIGTERM once, waiting (relay up)"
        elif [ $((t % 300)) -lt 15 ]; then
          diag "  still waiting on pid $pid (${t}s, relay up)"
        fi
      else
        # tunnel gone: the compile can never return and there is no live
        # relay state left to wedge — reap the zombie claimant
        diag "  relay dead at ${t}s — SIGKILL pid $pid"
        kill -9 "$pid" 2>/dev/null
      fi
    fi
  done
  wait "$pid"
  return $?
}

say "probe"
timeout 150 python bench.py --probe >> "$LOG" 2>&1 || { say "probe dead rc=$?"; exit 1; }

# one archive per window: stale phase records from earlier windows would
# otherwise ride along into this window's bench_results_tpu_*.jsonl copy
: > .bench_results.jsonl

# 1. bench variants, proven-first, ONE serve child per variant so an
#    overrun never takes later variants down with it (soft budget 900 s,
#    first compiles can exceed 600 s through the remote compiler)
# xla:f32 first: it is the fastest compile (r3 CPU: 36 s vs pallas' larger
# Mosaic pipeline) and windows have closed within minutes — the ordering
# maximizes the chance that a short window still lands ONE device number.
for SPEC in xla:float32:default:64:20 pallas:float32:default:64:20 \
            xla:bfloat16:default:64:20 pallas:bfloat16:default:64:20; do
  say "serve $SPEC"
  run_capped 1500 python bench.py --serve "$SPEC" 1350 >> "$LOG" 2>&1
  say "serve $SPEC rc=$? (results in .bench_results.jsonl)"
  relay_up || { say "relay died after $SPEC — stopping claim attempts"; break; }
done
cp -f .bench_results.jsonl "$OUT/bench_results_tpu_$STAMP.jsonl" 2>/dev/null

relay_up || exit 2

# 2. time/memory matrix on-chip (real peak HBM per N/remat/kernel combo)
say "memory matrix (tpu)"
run_capped 5400 python tools/memory_matrix.py --device tpu \
  --out "$OUT/memory_matrix_tpu_$STAMP.jsonl" >> "$LOG" 2>&1
say "memory matrix rc=$?"
relay_up || exit 2

# 3. pallas-vs-xla step time, incl. the block-sparsity floor sweep
#    (VERDICT r3 #2: does the data-dependent tile skip pay on the MXU?)
for ARGS in "--backend pallas --noise_mode counter --floor 0.01" \
            "--backend pallas --noise_mode counter --floor 0.0" \
            "--backend xla --noise_mode counter"; do
  for LEN in "" "--max_src_len 512"; do
    say "time_memory $ARGS $LEN"
    run_capped 1500 python tools/time_memory.py --config python $ARGS $LEN \
      --batch 64 --reps 5 --steps 4 >> "$OUT/time_memory_tpu_$STAMP.jsonl" 2>>"$LOG"
    relay_up || { say "relay died in time_memory sweep"; exit 2; }
  done
done

# 4. full-dims real-data training on the chip (background; runs as long as
#    the window lasts — resume-capable via orbax)
say "launching full-dims train_real on axon"
nohup python tools/train_real.py --data_dir ./data/stdlib_python \
  --variant sbm --full_dims --backend pallas --platform axon \
  --epochs 40 --val_interval 5 --out ./outputs/real_stdlib_tpu \
  > "$OUT/train_tpu_$STAMP.log" 2>&1 &
say "done (train pid $!)"

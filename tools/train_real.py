"""Bounded-budget training on the REAL stdlib corpus (VERDICT r2 item 3).

Trains a CPU-sized instance of the reference architecture on the corpus
produced by ``tools/build_real_corpus.py`` and records the evidence:
per-epoch loss / val-BLEU JSONL plus the final ``predict_results_*.json``
test dump (ref ``script/train.py:294-308``).

The model dims are scaled (SBM 256-wide, 2+2 layers) so a real multi-epoch
run fits a CPU wall-clock budget — the corpus, loop, decode and metrics are
the full product path (``csat_tpu.train``), not a test fixture.

Usage::

    python tools/train_real.py --data_dir ./data/stdlib_python \
        --variant full_att --epochs 24 --out ./outputs/real_stdlib
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--data_dir", required=True)
    p.add_argument("--variant", choices=["full_att", "sbm"], default="full_att")
    p.add_argument("--epochs", type=int, default=24)
    p.add_argument("--batch_size", type=int, default=32)
    p.add_argument("--learning_rate", type=float, default=3e-4)
    p.add_argument("--out", default="./outputs/real_stdlib")
    p.add_argument("--val_interval", type=int, default=4)
    p.add_argument("--save_interval", type=int, default=4)
    p.add_argument("--resume", action="store_true",
                   help="continue from the newest checkpoint in the output dir")
    p.add_argument("--platform", default="cpu",
                   help="jax platform; the bounded-budget run is CPU-sized")
    p.add_argument("--backend", default="", choices=["", "xla", "pallas"],
                   help="attention backend override (default: config's)")
    p.add_argument("--full_dims", action="store_true",
                   help="train at the reference config's full dims "
                        "(512-wide, 4+4 layers — TPU-sized) instead of the "
                        "CPU-budget 128-wide 2+2 stack")
    p.add_argument("--config", default="",
                   help="base config name override (e.g. python_seq for the "
                        "sequential-PE variant); default derives from "
                        "--variant")
    p.add_argument("--compute_dtype", default="",
                   choices=["", "float32", "bfloat16"],
                   help="activation dtype override (bf16 = the MXU path)")
    p.add_argument("--floor", default="",
                   help="sbm_floor override (e.g. 0.0 lifts the reference's "
                        "0.01 Bernoulli clamp — the block-sparsity quirk-fix)")
    p.add_argument("--seed", type=int, default=0,
                   help="override cfg.seed (0 = config default 2021) — for "
                        "seed-variance bounds on the paired BLEU tables")
    p.add_argument("--tag", default="",
                   help="suffix for the task/output dir (keeps ablation runs "
                        "from clobbering each other)")
    p.add_argument("--num_heads", type=int, default=0,
                   help="override head count (8 pairs with the torch "
                        "reference baseline, whose CSE hard-tiles 4+4 heads)")
    p.add_argument("--pad_row", default="", choices=["", "zero", "frozen"],
                   help="PAD-embedding-row mode (configs.Config.pad_row; "
                        "'frozen' = reference-parity garbage row)")
    p.add_argument("--width", type=int, default=0,
                   help="CPU-budget model width override (sbm_enc/hidden/"
                        "pegen = width, pe = width//2, ff = 4*width) — 64 "
                        "pairs with tools/train_torch_real.py --width 64 "
                        "on the scaled corpus")
    p.add_argument("--bucketing", action="store_true",
                   help="length-bucketed execution (csat_tpu/data/bucketing."
                        "py): per-bucket shapes + node-budget batch sizes")
    p.add_argument("--init_scheme", default="", choices=["", "flax", "reference"],
                   help="native init distributions (configs.Config."
                        "init_scheme; 'reference' = packed-fan decoder "
                        "q/k/v + uniform Linear biases, no torch needed)")
    p.add_argument("--init_from_torch", action="store_true",
                   help="initialize from an ACTUAL torch-reference init at "
                        "cfg.seed (ported via the parity-test converters): "
                        "removes every init-distribution difference at once "
                        "— torch's packed in_proj xavier fan (sqrt2 smaller "
                        "than per-matrix xavier on decoder q/k/v) and its "
                        "nonzero uniform Linear-bias init (VERDICT r4 "
                        "#2(b)). Requires num_heads=8 (reference CSE "
                        "hard-tiles 4+4).")
    args = p.parse_args()

    os.environ["JAX_PLATFORMS"] = args.platform
    import jax

    jax.config.update("jax_platforms", args.platform)

    from csat_tpu.utils.cache import enable_compilation_cache

    enable_compilation_cache()

    from csat_tpu.configs import get_config
    from csat_tpu.data.dataset import ASTDataset
    from csat_tpu.train import Trainer, run_test

    name = args.config or (
        "python_full_att" if args.variant == "full_att" else "python")
    from tools.pair_common import cpu_dims

    dims = {} if args.full_dims else cpu_dims(args.width or 128)
    if args.backend:
        dims["backend"] = args.backend
    if args.num_heads:
        dims["num_heads"] = args.num_heads
    if args.config:
        from csat_tpu.configs import get_config as _gc

        base = _gc(args.config)
        if base.pe_dim == 0:  # sequential PE: no pegen stack to size
            dims.pop("pe_dim", None)
            dims.pop("pegen_dim", None)
    if args.compute_dtype:
        dims["compute_dtype"] = args.compute_dtype
    if args.floor:
        dims["sbm_floor"] = float(args.floor)
    if args.seed:
        dims["seed"] = args.seed
    if args.pad_row:
        dims["pad_row"] = args.pad_row
    if args.init_scheme:
        dims["init_scheme"] = args.init_scheme
    if args.bucketing:
        dims["bucketing"] = True
    tag = f"_{args.tag}" if args.tag else ""
    cfg = get_config(
        name,
        data_dir=args.data_dir,
        task_name=f"real_stdlib_{args.variant}{tag}",
        batch_size=args.batch_size,
        num_epochs=args.epochs,
        learning_rate=args.learning_rate,
        val_interval=args.val_interval,
        save_interval=args.save_interval,
        output_dir=args.out,
        **dims,
    )

    out_dir = os.path.join(args.out, cfg.project_name, cfg.task_name)
    os.makedirs(out_dir, exist_ok=True)
    log_path = os.path.join(out_dir, "scalars.jsonl")
    log_f = open(log_path, "a")

    def log(msg: str) -> None:
        print(msg, flush=True)
        log_f.write(json.dumps({"t": round(time.time(), 1), "msg": msg}) + "\n")
        log_f.flush()

    trainer = Trainer(cfg, log=log)
    if args.init_from_torch:
        from tools.torch_init import torch_reference_init

        trainer.initial_params = torch_reference_init(
            cfg, trainer.src_vocab.size(), trainer.tgt_vocab.size())
        log("initialized from ported torch-reference init (tools/torch_init)")
    train_ds = ASTDataset(cfg, "train", trainer.src_vocab, trainer.tgt_vocab)
    val_ds = ASTDataset(cfg, "dev", trainer.src_vocab, trainer.tgt_vocab)
    test_ds = ASTDataset(cfg, "test", trainer.src_vocab, trainer.tgt_vocab)
    log(f"variant={args.variant} train={len(train_ds)} dev={len(val_ds)} "
        f"test={len(test_ds)} epochs={args.epochs}")

    from csat_tpu.train.checkpoint import make_checkpoint_fn

    t0 = time.monotonic()
    state, history = trainer.fit(
        train_ds, val_ds, checkpoint_fn=make_checkpoint_fn(trainer.output_dir),
        resume=args.resume,
    )
    log(f"training done in {time.monotonic() - t0:.0f}s best_bleu={history['best_bleu']:.4f}")

    scores = run_test(
        trainer.model, history["best_params"], test_ds, cfg, trainer.tgt_vocab,
        jax.random.key(cfg.seed), output_dir=out_dir,
    )
    import dataclasses

    summary = {
        "variant": args.variant,
        "config": {k: v for k, v in vars(args).items()},
        # the fully-resolved Config, so downstream tools (reeval_ckpt)
        # rebuild the run's exact hyperparameters instead of re-deriving
        # them from CLI sentinels where 0/"" are ambiguous (ADVICE r5)
        "resolved_config": dataclasses.asdict(cfg),
        "dims": {"sbm_enc_dim": cfg.sbm_enc_dim, "pe_dim": cfg.pe_dim,
                 "layers": [cfg.num_layers, cfg.sbm_layers, cfg.decoder_layers]},
        "loss_curve": history["loss"],
        "val_bleu": history["val_bleu"],
        "best_val_bleu": history["best_bleu"],
        "test_scores": scores,
        "wall_s": round(time.monotonic() - t0, 1),
    }
    with open(os.path.join(out_dir, "summary.json"), "w") as f:
        json.dump(summary, f, indent=1)
    print(json.dumps({"final": scores, "best_val_bleu": history["best_bleu"]}))


if __name__ == "__main__":
    main()

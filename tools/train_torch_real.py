"""Train the ACTUAL torch reference model on the stdlib corpus (VERDICT r3 #4).

The BLEU half of the north star ("within 0.1 of the PyTorch baseline",
``BASELINE.json``) needs the reference model *trained on the same corpus at
the same dims and budget* as ``tools/train_real.py`` — module-level parity
plus solo JAX curves cannot close it. The reference's own trainer is
ignite-based and ignite is absent from this image, so this tool drives the
reference's **model, optimizer and loss** (imported from
``/root/reference`` — the same imports the parity tests use; nothing is
copied into the framework) with a minimal loop that mirrors
``tools/train_real.py`` step-for-step:

* data: the SAME ``csat_tpu`` ASTDataset batches, converted to the
  reference's ``Data`` record shape (``base_data_set.py:60-75``);
* loss: reference ``LabelSmoothing(padding_idx=0, smoothing=cfg.smoothing)``
  + ``cfg.sw ·`` sparsity (``script/train.py:109``);
* optimizer: reference ``AdamW`` (``correct_bias=False``), constant lr —
  identical to ``csat_tpu.train.optimizer.adamw``;
* eval: reference ``GreedyGenerator`` decode, scored by the SAME
  ``csat_tpu.metrics`` pipeline (``bleu_output_transform`` +
  ``eval_accuracies``) used for the JAX runs.

Caveat recorded in the output: the reference CSE hard-tiles 4 L-heads +
4 T-heads (``module/csa_trans.py:206-211``), so this baseline runs at
``num_heads=8``; pair it with a JAX run at the same 8 heads
(``tools/train_real.py`` + the dims below).

    python tools/train_torch_real.py --data_dir ./data/stdlib_python \
        --epochs 12 --out ./results/real_stdlib_torch
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys
import time
import types

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
REF = "/root/reference"


def _import_reference():
    """Back-compat alias: the stub-importer now lives in tools.pair_common
    (shared by lockstep_ab / step0_probe / torch_init)."""
    from tools.pair_common import import_reference

    return import_reference()


def _to_torch(batch, torch):
    d = types.SimpleNamespace()
    import numpy as np

    d.src_seq = torch.from_numpy(np.asarray(batch.src_seq)).long()
    d.tgt_seq = torch.from_numpy(np.asarray(batch.tgt_seq)).long()
    d.L = torch.from_numpy(np.asarray(batch.L)).long()
    d.T = torch.from_numpy(np.asarray(batch.T)).long()
    d.L_mask = torch.from_numpy(np.asarray(batch.L_mask))
    d.T_mask = torch.from_numpy(np.asarray(batch.T_mask))
    d.num_node = torch.from_numpy(np.asarray(batch.num_node)).long()
    d.adj = torch.from_numpy(np.asarray(batch.adj))
    d.tree_pos = torch.from_numpy(np.asarray(batch.tree_pos))
    d.triplet = torch.from_numpy(np.asarray(batch.triplet)).long()
    target = torch.from_numpy(np.asarray(batch.target)).long()
    return d, target


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--data_dir", required=True)
    p.add_argument("--epochs", type=int, default=12)
    p.add_argument("--batch_size", type=int, default=32)
    p.add_argument("--learning_rate", type=float, default=3e-4)
    p.add_argument("--val_interval", type=int, default=4)
    p.add_argument("--out", default="./results/real_stdlib_torch")
    p.add_argument("--threads", type=int, default=0)
    p.add_argument("--width", type=int, default=128,
                   help="model width (sbm_enc/hidden/pegen; pe=width//2, "
                        "ff=4*width) — 64 is the scaled-corpus CPU budget")
    p.add_argument("--seed", type=int, default=0,
                   help="override cfg.seed (0 = config default 2021)")
    args = p.parse_args()

    import numpy as np
    import torch

    if args.threads:
        torch.set_num_threads(args.threads)
    ref_module, ref_utils, ref_optimizer = _import_reference()

    # jax is only used for dataset/config plumbing — keep it off the relay
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", "cpu")

    from csat_tpu.configs import get_config
    from csat_tpu.data.dataset import ASTDataset, iterate_batches
    from csat_tpu.data.vocab import load_vocab
    from csat_tpu.metrics import bleu_output_transform, eval_accuracies

    # train_real.py CPU dims, at the reference's mandatory 8 heads
    from tools.pair_common import cpu_dims

    over = {"seed": args.seed} if args.seed else {}
    cfg = get_config(
        "python", data_dir=args.data_dir, batch_size=args.batch_size,
        **{**cpu_dims(args.width), "num_heads": 8}, **over,
    )
    src_vocab, tgt_vocab = load_vocab(cfg.data_dir)
    train_ds = ASTDataset(cfg, "train", src_vocab, tgt_vocab)
    dev_ds = ASTDataset(cfg, "dev", src_vocab, tgt_vocab)
    test_ds = ASTDataset(cfg, "test", src_vocab, tgt_vocab)

    from tools.pair_common import build_reference_model

    model = build_reference_model(
        ref_module, cfg, src_vocab.size(), tgt_vocab.size())
    n_param = sum(t.numel() for t in model.parameters())
    optimizer = ref_optimizer.AdamW(
        model.parameters(), lr=args.learning_rate, correct_bias=False)
    criterion = ref_utils.label_smooth.LabelSmoothing(
        padding_idx=0, smoothing=cfg.smoothing)

    os.makedirs(args.out, exist_ok=True)
    log_f = open(os.path.join(args.out, "scalars.jsonl"), "a")

    def log(msg):
        print(msg, flush=True)
        log_f.write(json.dumps({"t": round(time.time(), 1), "msg": msg}) + "\n")
        log_f.flush()

    def evaluate(ds, max_batches=None):
        model.eval()
        gen = ref_module.base_seq2seq.GreedyGenerator(model, cfg.max_tgt_len)
        hyps, refs = [], []
        with torch.no_grad():
            for bi, batch in enumerate(
                iterate_batches(ds, cfg.batch_size, shuffle=False,
                                drop_last=False)):
                if max_batches and bi >= max_batches:
                    break
                d, target = _to_torch(batch, torch)
                ys = gen(d).numpy()
                h, r = bleu_output_transform(ys, np.asarray(batch.target),
                                             tgt_vocab.i2w)
                hyps.extend(h)
                refs.extend(r)
        hypotheses = {i: [" ".join(x)] for i, x in enumerate(hyps)}
        references = {i: [" ".join(x)] for i, x in enumerate(refs)}
        bleu, rouge_l, meteor, _, _ = eval_accuracies(hypotheses, references)
        model.train()
        return bleu, rouge_l, meteor

    log(f"torch reference baseline: train={len(train_ds)} dev={len(dev_ds)} "
        f"test={len(test_ds)} epochs={args.epochs} params={n_param}")
    t0 = time.monotonic()
    history = {"loss": [], "val_bleu": []}
    best_bleu, best_state = -1.0, None
    model.train()
    for epoch in range(args.epochs):
        te = time.monotonic()
        losses = []
        for batch in iterate_batches(train_ds, cfg.batch_size, shuffle=True,
                                     seed=cfg.seed + epoch):
            d, target = _to_torch(batch, torch)
            out, sparsity, _, _, _ = model(d)
            nll = criterion(out.reshape(-1, out.size(-1)), target.reshape(-1))
            loss = nll + cfg.sw * sparsity
            optimizer.zero_grad()
            loss.backward()
            optimizer.step()
            losses.append(float(nll.detach()))
        mean_loss = float(np.mean(losses))
        history["loss"].append(mean_loss)
        log(f"epoch {epoch}: loss {mean_loss:.4f} wall {time.monotonic() - te:.0f}s")
        if (epoch + 1) % args.val_interval == 0 or epoch == args.epochs - 1:
            bleu, _, _ = evaluate(dev_ds)
            history["val_bleu"].append([epoch, bleu])
            log(f"epoch {epoch}: dev BLEU {bleu:.4f}")
            if bleu > best_bleu:
                best_bleu = bleu
                best_state = {k: v.detach().clone()
                              for k, v in model.state_dict().items()}

    if best_state is not None:
        model.load_state_dict(best_state)
        # persist for decode-seed sweeps (r5: eval samples the SBM graph, so
        # test BLEU carries σ≈0.2-0.3 decode noise — fair comparisons need
        # the torch checkpoint re-decodable, not just its single draw)
        torch.save(best_state, os.path.join(args.out, "best_model.pt"))
    bleu, rouge_l, meteor = evaluate(test_ds)
    summary = {
        "framework": "torch-reference",
        "device": "cpu",
        "num_heads_note": "reference CSE hard-tiles 4+4 heads; run pairs "
                          "with a num_heads=8 JAX run",
        "config": vars(args),
        "dims": {"sbm_enc_dim": cfg.sbm_enc_dim, "pe_dim": cfg.pe_dim,
                 "pegen_dim": cfg.pegen_dim, "hidden": cfg.hidden_size,
                 "heads": cfg.num_heads,
                 "layers": [cfg.num_layers, cfg.sbm_layers, cfg.decoder_layers]},
        "n_param": n_param,
        "loss_curve": history["loss"],
        "val_bleu": history["val_bleu"],
        "best_val_bleu": best_bleu,
        "test_scores": {"bleu": bleu, "rouge_l": rouge_l, "meteor": meteor},
        "wall_s": round(time.monotonic() - t0, 1),
    }
    with open(os.path.join(args.out, "summary.json"), "w") as f:
        json.dump(summary, f, indent=1)
    print(json.dumps({"final": summary["test_scores"],
                      "best_val_bleu": best_bleu}))


if __name__ == "__main__":
    main()

"""Shared jax-free helpers for the perf tooling.

Kept free of ``import jax`` on purpose: the bench parent and the matrix
driver import from here without paying backend-plugin costs — only child
processes touch jax.
"""

from __future__ import annotations

import os

__all__ = ["cpu_child_env", "xla_mem"]


def cpu_child_env() -> dict:
    """Env for CPU-only child interpreters: skips the axon PJRT plugin
    entirely. The baked sitecustomize registers the plugin in EVERY python
    process (gated on ``PALLAS_AXON_POOL_IPS`` truthiness), and when the
    relay is half-dead its retry loop hangs interpreter startup for minutes
    (observed r5) — this is the single shared off-switch recipe."""
    return dict(os.environ, PALLAS_AXON_POOL_IPS="", JAX_PLATFORMS="cpu")


def xla_mem(compiled) -> dict:
    """XLA's compiled-program memory analysis — the static allocation plan
    (argument/output/temp/alias bytes) that decides HBM fit at compile time
    on TPU. Unlike runtime ``memory_stats()`` this works on every backend,
    so the CPU matrix gets real peak numbers too: ``static_peak_gb`` =
    arguments + outputs + temps − aliased (donation), and ``xla_temp_gb``
    alone isolates the transient intermediates that remat and the flash
    kernel exist to remove (the (B,H,N,N) tensors)."""
    try:
        ma = compiled.memory_analysis()
        if ma is None:
            return {}
        arg = int(ma.argument_size_in_bytes)
        out = int(ma.output_size_in_bytes)
        tmp = int(ma.temp_size_in_bytes)
        ali = int(ma.alias_size_in_bytes)
        return {
            "xla_arg_gb": round(arg / 2**30, 3),
            "xla_out_gb": round(out / 2**30, 3),
            "xla_temp_gb": round(tmp / 2**30, 3),
            "xla_alias_gb": round(ali / 2**30, 3),
            "static_peak_gb": round((arg + out + tmp - ali) / 2**30, 3),
        }
    except Exception as e:  # noqa: BLE001 — best-effort telemetry
        return {"xla_mem_error": str(e)[:160]}
